"""Frame batching (backend/framebatch.py): N independent streams whose
chunk-machine device steps ride single vmapped calls — the TPU answer
to the reference scaling frames with per-pipeline threads (SURVEY.md
§2.2). Contract: results are bit-identical to running each frame alone,
and for same-shape frames the device-call count stays at the
single-frame count (VERDICT r3 next #3: 16 captures <= 2x the calls of
one)."""

import os

import numpy as np
import pytest

from ziria_tpu.backend import chunked as C
from ziria_tpu.backend import hybrid as H
from ziria_tpu.backend.framebatch import StepBatcher, run_many
from ziria_tpu.frontend import compile_source
from ziria_tpu.interp.interp import run

TAKE_BRANCH_SRC = """
let comp main = read[int32] >>> {
  var acc : arr[512] int32;
  var s : int32 := 0;
  times 256 {
    x <- take;
    do {
      if (x % 2 == 0) then { s := s + x } else { s := s + 1 };
      acc[s % 512] := x
    }
  };
  times 256 { emit acc[(s + 255) % 512]; do { s := s + 3 } }
} >>> write[int32]
"""

WHILE_SRC = """
let comp main = read[int32] >>> {
  var s : int32 := 0;
  var armed : bool := false;
  while (!armed) {
    x <- take;
    do {
      s := s + x * x - (s / 7);
      if (s % 1000 > 900) then { armed := true }
    }
  };
  emit s;
  (w : arr[20] int32) <- takes 20;
  do { for k in [0, 20] { s := s + w[k] } };
  emit s
} >>> write[int32]
"""


def _check_many(hyb, frames, **kw):
    want = [run(hyb, list(f)) for f in frames]
    b = StepBatcher(len(frames))
    got = run_many(hyb, frames, batcher=b, **kw)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w.out_array()),
                                      np.asarray(g.out_array()))
        assert w.terminated_by == g.terminated_by
        assert w.value == g.value
    return b


def test_lockstep_frames_exact_and_call_budget():
    hyb = H.hybridize(compile_source(TAKE_BRANCH_SRC).comp)
    frames = [(np.arange(300, dtype=np.int32) * k + k) % 251
              for k in range(1, 9)]
    C.STATS["device_calls"] = 0
    run(hyb, list(frames[0]))
    single = C.STATS["device_calls"]
    assert single >= 2                     # take machine + emit machine
    b = _check_many(hyb, frames)
    # 8 lockstep frames cost the SAME number of device calls as one
    assert b.device_calls <= single
    assert max(b.group_sizes) == len(frames)


def test_ragged_frame_lengths_exact():
    # divergent EOF tails: some frames starve the take loop mid-way and
    # finish on the interpreter; others run full chunks
    hyb = H.hybridize(compile_source(TAKE_BRANCH_SRC).comp)
    frames = [np.arange(n, dtype=np.int32) % 97
              for n in (37, 150, 255, 256, 300, 512)]
    _check_many(hyb, frames)


def test_while_machines_divergent_arming():
    # While machines arm at data-dependent points: frames park different
    # numbers of times and drift across program points
    hyb = H.hybridize(compile_source(WHILE_SRC).comp)
    rng = np.random.default_rng(3)
    frames = [rng.integers(0, 50, 400).astype(np.int32) for _ in range(6)]
    _check_many(hyb, frames)


def test_single_frame_passthrough():
    hyb = H.hybridize(compile_source(TAKE_BRANCH_SRC).comp)
    xs = np.arange(300, dtype=np.int32)
    want = run(hyb, list(xs))
    (got,) = run_many(hyb, [xs])
    np.testing.assert_array_equal(np.asarray(want.out_array()),
                                  np.asarray(got.out_array()))
    assert run_many(hyb, []) == []


def test_interp_tail_under_batching():
    # the r4 staleness fix must hold when tails run on batched frames:
    # worst-case take 2 / actual take 1, every frame ends in a tail
    src = """
    let comp main = read[int32] >>> {
      var s : int32 := 0;
      times 256 {
        x <- take;
        do { s := s + 1 };
        if (x < 0) then { y <- take; do { s := s + y } }
      };
      emit s * s
    } >>> write[int32]
    """
    hyb = H.hybridize(compile_source(src).comp)
    frames = [np.arange(n, dtype=np.int32) for n in (256, 256, 257, 300)]
    _check_many(hyb, frames)


def test_wifi_rx_zir_16_captures():
    """VERDICT r3 #3 done-criterion: 16 independent captures through the
    in-language receiver cost <= 2x the single-frame device-call count,
    bit-exact vs per-frame runs."""
    from ziria_tpu.frontend import compile_file
    from ziria_tpu.phy import channel
    from ziria_tpu.phy.wifi import rx
    from ziria_tpu.utils.bits import bytes_to_bits

    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "wifi_rx.zir")
    hyb = H.hybridize(compile_file(src).comp)

    mbps, n_bytes = 24, 60
    caps = [channel.impaired_capture(mbps, n_bytes, seed=100 + k,
                                     add_fcs=True)
            for k in range(16)]
    for psdu, xi in caps:
        assert rx.receive(xi.astype(np.float32)).ok

    # single-frame path: ground truth + call count (after warm-up so
    # compile-time retries don't inflate the count)
    run(hyb, [p for p in caps[0][1]])
    C.STATS["device_calls"] = 0
    want = [run(hyb, [p for p in xi]).out_array() for _psdu, xi in caps]
    single_avg = C.STATS["device_calls"] / len(caps)
    for (psdu, _xi), w in zip(caps, want):
        np.testing.assert_array_equal(np.asarray(w, np.uint8),
                                      np.asarray(bytes_to_bits(psdu)))

    b = StepBatcher(len(caps))
    got = run_many(hyb, [[p for p in xi] for _psdu, xi in caps],
                   batcher=b)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w, np.uint8),
                                      np.asarray(g.out_array(), np.uint8))
    assert b.device_calls <= 2 * single_avg, (
        f"16 captures took {b.device_calls} device calls; single-frame "
        f"average is {single_avg}")


def test_mixed_rate_captures_exact():
    # different rates/lengths => frames diverge structurally (different
    # jit keys and chunk widths); correctness must survive regrouping
    from ziria_tpu.frontend import compile_file
    from ziria_tpu.phy import channel
    from ziria_tpu.utils.bits import bytes_to_bits

    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "wifi_rx.zir")
    hyb = H.hybridize(compile_file(src).comp)
    caps = [channel.impaired_capture(m, nb, seed=m, add_fcs=True)
            for m, nb in ((6, 30), (24, 60), (54, 90))]
    got = run_many(hyb, [[p for p in xi] for _psdu, xi in caps])
    for (psdu, _xi), g in zip(caps, got):
        np.testing.assert_array_equal(
            np.asarray(g.out_array(), np.uint8),
            np.asarray(bytes_to_bits(psdu)))


def test_vmap_failure_degrades_to_singles():
    # code review r4: a vmap-only failure must not abort frames whose
    # per-frame step works, nor mark the shared machine broken — the
    # batcher retries each lane unbatched
    hyb = H.hybridize(compile_source(TAKE_BRANCH_SRC).comp)
    frames = [(np.arange(300, dtype=np.int32) * k + 1) % 97
              for k in range(1, 5)]
    want = [run(hyb, list(f)) for f in frames]

    class BrokenVmap(StepBatcher):
        def _vfn(self, node, key):
            def boom(*a):
                raise RuntimeError("synthetic vmap failure")
            return boom

    b = BrokenVmap(len(frames))
    got = run_many(hyb, frames, batcher=b)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w.out_array()),
                                      np.asarray(g.out_array()))
    assert all(s == 1 for s in b.group_sizes)
    # the machines must still be healthy for later batched runs
    b2 = StepBatcher(len(frames))
    got2 = run_many(hyb, frames, batcher=b2)
    for w, g in zip(want, got2):
        np.testing.assert_array_equal(np.asarray(w.out_array()),
                                      np.asarray(g.out_array()))
    assert max(b2.group_sizes) == len(frames)


def test_one_frame_error_surfaces_others_complete():
    # a genuine program error in ONE frame (the language `error`
    # builtin, data-triggered — runs interpreter-side since effects
    # are unstageable) must surface from run_many after the other
    # frames finish — no deadlock, no silent swallow
    src = """
    let comp main = read[int32] >>> {
      var s : int32 := 0;
      times 300 {
        x <- take;
        do { s := s + x }
      };
      if (s < 0) then { do { error "negative checksum" } };
      emit s
    } >>> write[int32]
    """
    hyb = H.hybridize(compile_source(src).comp)
    good = [np.arange(300, dtype=np.int32) % 64 for _ in range(3)]
    bad = np.full(300, -1, np.int32)           # s goes negative
    with pytest.raises(Exception, match="negative checksum"):
        run(hyb, list(bad))                    # solo errors too
    with pytest.raises(Exception, match="negative checksum"):
        run_many(hyb, good[:1] + [bad] + good[1:],
                 batcher=StepBatcher(4))


def test_max_out_limit_under_batching():
    # infinite transformers stop at max_out per frame; a frame whose
    # generator is abandoned mid-stream must not wedge the batcher
    src = """
    let comp main = read[int32] >>> repeat {
      var s : int32 := 0;
      times 64 { x <- take; do { s := s + x } };
      times 64 { emit s; do { s := s - 1 } }
    } >>> write[int32]
    """
    hyb = H.hybridize(compile_source(src).comp)
    frames = [(np.arange(640, dtype=np.int32) * k) % 101
              for k in range(1, 5)]
    want = [run(hyb, list(f), max_out=100) for f in frames]
    got = run_many(hyb, frames, max_out=100,
                   batcher=StepBatcher(len(frames)))
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w.out_array()),
                                      np.asarray(g.out_array()))
        assert w.terminated_by == g.terminated_by == "limit"
