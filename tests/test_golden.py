"""Checked-in golden files: the reference's (.blk, .infile,
.outfile.ground) discipline (SURVEY.md §4).

Each example runs through the CLI jit backend against the committed
input, and the output must match the committed ground truth (produced
by the interpreter oracle via examples/make_golden.py) under the
BlinkDiff-style comparator: exact for integer/bit streams, atol=1 for
quantized complex. Exceptions: cases in make_golden.INTERP_CASES
replay on the interpreter (whole-frame programs whose fully-unrolled
jit graphs take minutes of XLA compile on CPU) — for those this test
pins CLI file I/O + determinism only — and cases in HYBRID_CASES
(dynamic-control programs, e.g. the flagship receiver) replay on the
hybrid backend, pinning interpreter-vs-hybrid equality through the
committed files."""

import os

import numpy as np
import pytest

from ziria_tpu.frontend import compile_file
from ziria_tpu.runtime.buffers import StreamSpec, read_stream
from ziria_tpu.runtime.cli import main as cli_main
from ziria_tpu.utils.diff import stream_diff

HERE = os.path.dirname(__file__)
EXAMPLES = os.path.abspath(os.path.join(HERE, "..", "examples"))
GOLD = os.path.join(EXAMPLES, "golden")


def _generator_cases():
    """The (name, mode) table comes from the generator itself so the
    file modes can never drift between generation and replay."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "make_golden", os.path.join(EXAMPLES, "make_golden.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return ({name: mode for name, _ty, _mk, mode in mod.CASES},
            mod.FXP_CASES, mod.INTERP_CASES, mod.AUTOLUT_CASES,
            mod.HYBRID_CASES)


(_MODES, _FXP_CASES, _INTERP_CASES, _AUTOLUT_CASES,
 _HYBRID_CASES) = _generator_cases()

# quantized complex streams compare with atol=1; float LLR outputs
# tolerate interp-f64 vs jit-f32 rounding; everything else exact
_ATOL = {"fft64": 1.0, "qam16": 1.0, "pilot_track": 1.0,
         "wifi_tx_full": 1.0,
         "demap_bpsk": 1e-4, "demap_qpsk": 1e-4,
         "demap_qam16": 1e-4, "demap_qam64": 1e-4}

CASES = [(name, mode, _ATOL.get(name, 0.0))
         for name, mode in _MODES.items()]


@pytest.mark.parametrize("name,mode,atol", CASES)
def test_golden(name, mode, atol, tmp_path):
    src = os.path.join(EXAMPLES, f"{name}.zir")
    infile = os.path.join(GOLD, f"{name}.infile")
    ground = os.path.join(GOLD, f"{name}.outfile.ground")
    assert os.path.exists(infile) and os.path.exists(ground), \
        f"golden files missing for {name}; run examples/make_golden.py"

    outf = tmp_path / f"{name}.out"
    backend = ("interp" if name in _INTERP_CASES else
               "hybrid" if name in _HYBRID_CASES else "jit")
    argv = [
        f"--src={src}", "--input=file", f"--input-file-name={infile}",
        f"--input-file-mode={mode}", "--output=file",
        f"--output-file-name={outf}", f"--output-file-mode={mode}",
        f"--backend={backend}",
    ]
    if name in _FXP_CASES:
        argv.append("--fxp-complex16")
    if name in _AUTOLUT_CASES:
        argv.append("--autolut")
    rc = cli_main(argv)
    assert rc == 0

    prog = compile_file(src, fxp_complex16=name in _FXP_CASES)
    got = read_stream(StreamSpec(ty=prog.out_ty, path=str(outf),
                                 mode=mode))
    want = read_stream(StreamSpec(ty=prog.out_ty, path=ground, mode=mode))
    if atol:
        rep = stream_diff(got.astype(np.float64), want.astype(np.float64),
                          atol=atol, name=name)
    else:
        rep = stream_diff(got, want, name=name)
    assert rep, rep.message


def test_wifi_rx_golden_with_windowed_viterbi(tmp_path, monkeypatch):
    """--viterbi-window routes the compiled DSL receiver's viterbi_soft
    ext through the sliding-window parallel decode; the golden capture
    must replay byte-identically (same driver invocation the judge
    uses, plus the flag)."""
    name, mode = "wifi_rx", "bin"
    src = os.path.join(EXAMPLES, f"{name}.zir")
    infile = os.path.join(GOLD, f"{name}.infile")
    ground = os.path.join(GOLD, f"{name}.outfile.ground")
    outf = tmp_path / "out.bin"
    monkeypatch.delenv("ZIRIA_VITERBI_WINDOW", raising=False)
    rc = cli_main([
        f"--src={src}", "--input=file", f"--input-file-name={infile}",
        f"--input-file-mode={mode}", "--output=file",
        f"--output-file-name={outf}", f"--output-file-mode={mode}",
        "--backend=hybrid", "--viterbi-window=256", "--platform=cpu",
    ])
    assert rc == 0
    with open(outf, "rb") as f1, open(ground, "rb") as f2:
        assert f1.read() == f2.read()
