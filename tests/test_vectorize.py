"""Vectorizer tests: scale-factor search, widening rewrite, mitigators,
mixed static/dynamic execution.

The reference's vectorizer invariant (SURVEY.md §4): output is identical
with and without vectorization, for every width choice. The matrix here
is {interpreter oracle} x {widen(w) for several w} x {per-stage widths
with mitigators} x {run_vect planned execution}.
"""

import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit, run_vect
from ziria_tpu.core import ir
from ziria_tpu.core.card import steady_state
from ziria_tpu.core.vectorize import (
    mitigator,
    search_width,
    utility,
    vectorize,
    widen,
)
from ziria_tpu.interp.interp import run
from ziria_tpu.utils.diff import assert_stream_eq


def _fir_prog():
    import jax.numpy as jnp
    taps = np.array([0.25, 0.5, 0.25], dtype=np.float32)

    def fir_step(state, x):
        state = jnp.roll(state, 1).at[0].set(x)
        return state, (state * taps).sum()

    return z.pipe(z.zmap(lambda x: x * 2.0),
                  z.map_accum(fir_step, np.zeros(3, np.float32)),
                  z.zmap(lambda x: x + 1.0))


def _rate_change_prog():
    """3->1 then 1->2: steady state reps (1, 1, 3) on a 3-in chain."""
    import jax.numpy as jnp
    return z.pipe(
        z.zmap(lambda v: v.sum(), in_arity=3, out_arity=1, name="sum3"),
        z.zmap(lambda x: jnp.stack([x, -x]), in_arity=1, out_arity=2,
               name="split2"),
    )


# ----------------------------------------------------------------- planning


def test_search_width_prefers_amortization():
    prog = z.pipe(z.zmap(lambda x: x + 1), z.zmap(lambda x: x * 2))
    ss = steady_state(ir.pipeline_stages(prog))
    W, cands = search_width(ss, ir.pipeline_stages(prog))
    # stateless chain: width should grow well past 1 to amortize the
    # per-step overhead
    assert W >= 256
    assert all(c[1] != float("-inf") or c[0] == cands[-1][0] for c in cands)


def test_search_width_respects_vmem_budget():
    prog = z.pipe(z.zmap(lambda x: x + 1), z.zmap(lambda x: x * 2))
    ss = steady_state(ir.pipeline_stages(prog))
    budget = 1 << 12  # 4 KiB
    item_bytes = 4
    W, cands = search_width(ss, ir.pipeline_stages(prog),
                            item_bytes=item_bytes, vmem_budget=budget)
    assert W * ss.take * item_bytes <= budget
    # the search stopped at the first infeasible candidate
    assert cands[-1][1] == float("-inf")


def test_utility_stateful_narrower_than_stateless():
    """A scan-dominated segment should pick a narrower width than a pure
    vmap segment: sequential firings stop paying once overhead is
    amortized."""
    stateless = z.pipe(z.zmap(lambda x: x + 1), z.zmap(lambda x: x * 2))
    stateful = _fir_prog()
    ss_l = steady_state(ir.pipeline_stages(stateless))
    ss_f = steady_state(ir.pipeline_stages(stateful))
    W_l, _ = search_width(ss_l, ir.pipeline_stages(stateless))
    W_f, _ = search_width(ss_f, ir.pipeline_stages(stateful))
    assert W_f <= W_l


def test_vectorize_dump_lists_candidates():
    plan = vectorize(_fir_prog())
    text = plan.dump()
    assert "width" in text and "utility=" in text and "W=1" in text
    assert len(plan.segments) == 1
    seg = plan.segments[0]
    assert not seg.dynamic
    assert any(W == seg.width for W, _, _ in seg.candidates)


def test_vectorize_splits_at_dynamic_stage():
    dyn = ir.Repeat(z.seq(z.let("x", z.take,
                                z.while_loop(lambda env: False,
                                             z.ret(0))),
                          z.emit(lambda env: env["x"])))
    prog = z.pipe(z.zmap(lambda x: x + 1), dyn, z.zmap(lambda x: x * 2))
    plan = vectorize(prog)
    kinds = [seg.dynamic for seg in plan.segments]
    assert kinds == [False, True, False]
    assert "DYNAMIC" in plan.dump()


# ----------------------------------------------------------------- widening


@pytest.mark.parametrize("w", [1, 2, 4, 8])
def test_widen_invariance_uniform(w):
    prog = _fir_prog()
    xs = np.arange(64, dtype=np.float32)
    want = run(prog, list(xs)).out_array()

    wide = widen(prog, w)
    blocks = xs if w == 1 else xs.reshape(-1, w)
    got_i = np.asarray(run(wide, list(blocks)).out_array()).reshape(-1)
    assert_stream_eq(got_i, want, atol=1e-6, rtol=1e-6, name=f"interp w={w}")

    got_j = np.asarray(run_jit(wide, blocks)).reshape(-1)
    assert_stream_eq(got_j, want, atol=1e-6, rtol=1e-6, name=f"jit w={w}")


@pytest.mark.parametrize("w", [2, 4])
def test_widen_rate_change_stage(w):
    """Widening a stage with in_arity/out_arity > 1 keeps raw stream
    order (the take->takes reshape algebra)."""
    prog = _rate_change_prog()
    xs = np.arange(48, dtype=np.float32)
    want = run(prog, list(xs)).out_array()
    wide = widen(prog, w)
    blocks = xs.reshape(-1, w)
    got = np.asarray(run_jit(wide, blocks)).reshape(-1)
    assert_stream_eq(got, want, name=f"rate-change w={w}")


def test_widen_per_stage_inserts_mitigator():
    prog = z.pipe(z.zmap(lambda x: x + 1, name="a"),
                  z.zmap(lambda x: x * 2, name="b"))
    wide = widen(prog, {0: 4, 1: 2})
    labels = [s.label() for s in ir.pipeline_stages(wide)]
    assert any("mitigate[4->2]" in l for l in labels)

    xs = np.arange(32, dtype=np.float32)
    want = run(prog, list(xs)).out_array()
    got = np.asarray(run_jit(wide, xs.reshape(-1, 4))).reshape(-1)
    assert_stream_eq(got, want, name="mitigated")


def test_mitigator_is_stream_identity():
    m = mitigator(6, 4)
    xs = np.arange(24, dtype=np.int32).reshape(-1, 6)
    out = np.asarray(run_jit(m, xs))
    assert out.shape == (6, 4)
    np.testing.assert_array_equal(out.reshape(-1), np.arange(24))


def test_widen_repeat_stage():
    body = z.seq(z.let("x", z.take, z.emit(lambda env: env["x"] + 10.0)))
    prog = z.pipe(z.repeat(body), z.zmap(lambda x: x * 0.5))
    xs = np.arange(16, dtype=np.float32)
    want = run(prog, list(xs)).out_array()
    got = np.asarray(run_jit(widen(prog, 4), xs.reshape(-1, 4))).reshape(-1)
    assert_stream_eq(got, want, name="widened repeat")


# ----------------------------------------------------------- mixed execution


def test_run_vect_fully_static_matches_oracle():
    prog = _fir_prog()
    xs = np.arange(256, dtype=np.float32)
    want = run(prog, list(xs)).out_array()
    got = run_vect(prog, xs)
    assert_stream_eq(np.asarray(got), want, atol=1e-6, rtol=1e-6,
                     name="run_vect static")


def test_run_vect_bridges_dynamic_segment():
    # middle stage: data-dependent while loop (emit x, but first loop
    # x times decrementing a ref) — interpreter-only
    def body():
        return z.seq(
            z.let("x", z.take,
                  z.let_ref("n", lambda env: int(env["x"]) % 3,
                            z.seq(z.while_loop(
                                lambda env: env["n"] > 0,
                                z.assign("n", lambda env: env["n"] - 1)),
                                z.emit(lambda env: env["x"])))))

    dyn = ir.Repeat(body())
    prog = z.pipe(z.zmap(lambda x: x + 1), dyn,
                  z.zmap(lambda x: x * 2))
    xs = np.arange(32, dtype=np.int64)
    want = run(prog, list(xs)).out_array()
    got = run_vect(prog, xs)
    assert_stream_eq(np.asarray(got), want, name="run_vect mixed")


def test_run_vect_rate_change_pipeline():
    prog = _rate_change_prog()
    xs = np.arange(96, dtype=np.float32)
    want = run(prog, list(xs)).out_array()
    got = run_vect(prog, xs)
    assert_stream_eq(np.asarray(got), want, name="run_vect rates")


def test_model_constants_platform_keyed_and_measured():
    """VERDICT r4 next #6: the utility constants carry a measured
    pedigree per platform. The cpu row is fitted from the committed
    VECT_CALIB_CPU.json probe tables; the tpu row stays an
    architectural estimate until VECT_CALIB.json (chip fit) lands, at
    which point model_constants() prefers its fitted_constants block
    automatically."""
    from ziria_tpu.core.vectorize import MODEL_CONSTANTS, model_constants

    cpu = model_constants("cpu")
    tpu = model_constants("tpu")
    assert "measured" in cpu["pedigree"]
    assert (cpu["vpu_parallel"], cpu["step_overhead"]) != \
        (tpu["vpu_parallel"], tpu["step_overhead"])
    # under the test conftest jax is pinned to cpu -> active platform
    # resolves to the measured row
    assert model_constants()["pedigree"] == cpu["pedigree"]
    # a measured fact the fit encodes: CPU per-step overhead is far
    # larger relative to item cost than the TPU guess assumed, so a
    # scan-bound pipeline widens its pick under the cpu constants
    import ziria_tpu as z
    from ziria_tpu.core import ir as _ir
    from ziria_tpu.core.card import steady_state

    prog = z.pipe(z.map_accum(lambda s, x: (s + x, s + x), 0.0))
    ss = steady_state(_ir.pipeline_stages(prog))
    W_cpu, _ = search_width(ss, _ir.pipeline_stages(prog),
                            constants=MODEL_CONSTANTS["cpu"])
    W_tpu, _ = search_width(ss, _ir.pipeline_stages(prog),
                            constants=MODEL_CONSTANTS["tpu"])
    assert W_cpu > W_tpu
