"""The tier-1 lint gate: the whole ``ziria_tpu/`` tree lints CLEAN.

This is the CI teeth of jaxlint (docs/static_analysis.md): every
jit-factory cache key complete (R1), no host sync inside timed
regions (R2), every cached-jit dispatch observable (R3), env knobs
behind designated single readers and the cli scoped-env pattern (R4),
no array-keyed lru caches (R5). A finding here means either fix the
code or add a ``# ziria: lint-ignore[rule] reason`` pragma whose
justification survives review — never weaken the rule.

Pure AST, no jax import, runs in well under a second: cheap enough
that tier-1 pays it on every run.
"""

import os

import ziria_tpu
from ziria_tpu.analysis import lint_paths

PKG = os.path.dirname(os.path.abspath(ziria_tpu.__file__))


def test_tree_is_lint_clean():
    res = lint_paths([PKG])
    assert res.files > 50          # the walk really saw the tree
    rendered = "\n".join(f.render() for f in res.findings)
    assert not res.findings, (
        f"jaxlint found {len(res.findings)} finding(s) — fix them or "
        f"add a justified lint-ignore pragma:\n{rendered}")


def test_gate_matches_cli_contract():
    # `python -m ziria_tpu.analysis ziria_tpu/` exiting 0 is the
    # published acceptance surface; the gate and the CLI share
    # lint_paths, so pin the counts shape here too
    res = lint_paths([PKG])
    assert res.counts == {}
