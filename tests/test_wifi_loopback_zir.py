"""The in-language loopback program: examples/wifi_loopback.zir.

MAC-shaped frames ([rate, len, payload bits] in-band on an int32
stream) travel the COMPLETE PHY both directions inside one program:
fcs_add (the reference TX chain's leading crc block, SURVEY.md §3.5)
>>> tx_frame (lib/wifi_tx_lib.zir) >>> rx (lib/wifi_rx_lib.zir). The
assertion is identity: the emitted bits equal the payload bits, FCS
generated TX-side and validated+stripped RX-side. Also pins the
#include machinery the program is built on (SURVEY.md §2.3 — the
reference composes programs from block files via the preprocessor).
"""

import os

import numpy as np
import pytest

from ziria_tpu.frontend import ElabError, compile_file, compile_source
from ziria_tpu.interp.interp import run

SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                   "wifi_loopback.zir")


def _stream(frames):
    out = []
    for rate, bits in frames:
        out += [rate, len(bits) // 8] + list(bits)
    return [np.int32(x) for x in out]


def _payload(frames):
    return np.concatenate([np.asarray(b) for _r, b in frames])


def test_loopback_identity_two_rates():
    rs = np.random.RandomState(0)
    frames = [(6, rs.randint(0, 2, 8 * 20).tolist()),
              (24, rs.randint(0, 2, 8 * 30).tolist())]
    prog = compile_file(SRC)
    out = run(prog.comp, _stream(frames)).out_array()
    np.testing.assert_array_equal(np.asarray(out, np.uint8),
                                  _payload(frames))


@pytest.mark.parametrize("rate", [9, 12, 18, 36, 48, 54])
def test_loopback_identity_each_rate(rate):
    rs = np.random.RandomState(rate)
    n_bytes = int(rs.randint(8, 40))
    frames = [(rate, rs.randint(0, 2, 8 * n_bytes).tolist())]
    prog = compile_file(SRC)
    out = run(prog.comp, _stream(frames)).out_array()
    np.testing.assert_array_equal(np.asarray(out, np.uint8),
                                  _payload(frames))


def test_loopback_hybrid_matches():
    rs = np.random.RandomState(7)
    frames = [(12, rs.randint(0, 2, 8 * 24).tolist())]
    prog = compile_file(SRC)
    from ziria_tpu.backend import hybrid as HY
    out = run(HY.hybridize(prog.comp), _stream(frames)).out_array()
    np.testing.assert_array_equal(np.asarray(out, np.uint8),
                                  _payload(frames))


def test_loopback_bad_length_dropped_neighbors_survive():
    # an over-length frame is consumed whole by fcs_add, which forwards
    # length 0 so tx_frame rejects it deterministically TX-side (code
    # review r4: lengths in (LENMAX-4, LENMAX] previously reached the
    # air without an FCS); the next frame decodes intact
    rs = np.random.RandomState(9)
    bad = (24, rs.randint(0, 2, 8 * 253).tolist())   # > LENMAX - 4
    good = (6, rs.randint(0, 2, 8 * 16).tolist())
    prog = compile_file(SRC)
    out = run(prog.comp, _stream([bad, good])).out_array()
    np.testing.assert_array_equal(np.asarray(out, np.uint8),
                                  _payload([good]))


# ---- #include machinery -------------------------------------------------


def test_include_missing_file_is_located_error():
    with pytest.raises(ElabError, match=r"cannot include"):
        compile_source('#include "no_such_lib.zir"\n'
                       'let comp main = read[bit] >>> write[bit]',
                       base_dir=os.path.dirname(SRC))


def test_include_requires_file_compile():
    with pytest.raises(ElabError, match=r"file-based"):
        compile_source('#include "lib/wifi_tx_lib.zir"\n'
                       'let comp main = read[bit] >>> write[bit]')


def test_host_main_overrides_included(tmp_path):
    lib = tmp_path / "l.zir"
    lib.write_text("fun f(x: int32): int32 { return x + 1 }\n"
                   "let comp main = read[int32] >>> map f "
                   ">>> write[int32]\n")
    host = tmp_path / "m.zir"
    host.write_text('#include "l.zir"\n'
                    "fun g(x: int32): int32 { return f(x) * 10 }\n"
                    "let comp main = read[int32] >>> map g "
                    ">>> write[int32]\n")
    prog = compile_file(str(host))
    out = run(prog.comp, [np.int32(1), np.int32(2)]).out_array()
    np.testing.assert_array_equal(np.asarray(out), [20, 30])
