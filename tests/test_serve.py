"""Continuous-batching serving runtime (runtime/serve, ISSUE 13):
admission control with explicit backpressure, deterministic SLO
shedding, session eviction + checkpoint recovery, graceful drain, and
the dispatch-budget contract — session count never enters the
≤ 2-dispatches-per-chunk-step budget.

Two test families:

- STATE-MACHINE tests ride a stub receiver (no jax dispatch, no
  compile): admission/queue/reject, backlog/oversize bounds, deadline
  shedding under a fake clock, drain accounting, scrape format.
- FLEET tests ride the real `MultiStreamReceiver` at the suite-shared
  streaming geometry (chunk 4096 / window 1024 / K=8 / 12-byte+FCS
  PSDUs, S=8 lanes — the exact compile keys test_rx_multistream and
  test_resilience already pay for), pinning healthy-session
  bit-identity vs lone single-stream receivers, the evict→restore
  round trip, quarantine containment, and the dispatch budget under
  admission/eviction churn via ``dispatch.no_recompile``.
"""

import numpy as np
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import link
from ziria_tpu.runtime import resilience, serve
from ziria_tpu.utils import dispatch, faults

N_BYTES = 12
CHUNK, FRAME_LEN, K, S = 4096, 1024, 8, 8
GEO = dict(chunk_len=CHUNK, frame_len=FRAME_LEN,
           max_frames_per_chunk=K, check_fcs=True)


def _same(a, b) -> bool:
    return (a.start == b.start and a.result.ok == b.result.ok
            and a.result.rate_mbps == b.result.rate_mbps
            and a.result.length_bytes == b.result.length_bytes
            and np.array_equal(a.result.psdu_bits, b.result.psdu_bits)
            and a.result.crc_ok == b.result.crc_ok)


def _identical(got, want) -> None:
    assert [f.start for f in got] == [f.start for f in want]
    for a, b in zip(got, want):
        assert _same(a, b)


# ------------------------------------------------- stub (state machine)


class _StubStats:
    def __init__(self, chunk_steps):
        self.chunk_steps = chunk_steps


class _Stub:
    """Sample-count-only receiver: one token frame per consumed
    chunk; no device work. The serve layer must treat frames as
    opaque, so tokens suffice."""

    def __init__(self, s, chunk_len=256, frame_len=64):
        self.s, self.chunk_len = s, chunk_len
        self.stride = chunk_len - frame_len
        self._tails = [0] * s
        self._offsets = [0] * s
        self._steps = 0
        self.flushed = False
        self.restored = {}

    @property
    def stats(self):
        return _StubStats(self._steps)

    def quarantined(self, i):
        return False

    def push_many(self, slabs):
        for i, a in slabs.items():
            self._tails[i] += int(a.shape[0])
        out = []
        while any(t >= self.chunk_len for t in self._tails):
            self._steps += 1
            for i in range(self.s):
                if self._tails[i] >= self.chunk_len:
                    out.append((i, ("frame", i, self._offsets[i])))
                    self._tails[i] -= self.stride
                    self._offsets[i] += self.stride
        return out

    def drain_pending(self):
        return []

    def flush_stream(self, i):
        out = []
        if self._tails[i]:
            self._steps += 1
            out.append((i, ("frame", i, self._offsets[i])))
            self._tails[i] = 0
        return out

    def reset_stream(self, i):
        self._tails[i] = 0
        self._offsets[i] = 0
        return []

    def restore_stream(self, i, blob):
        self.restored[i] = blob
        return []

    def checkpoint(self, i):
        return b"blob", []

    def flush(self):
        self.flushed = True
        return []


def _stub_srv(n_lanes=2, clock=None, **kw):
    cfg = serve.ServeConfig(
        n_lanes=n_lanes, chunk_len=256, frame_len=64, queue_cap=2,
        max_slab_samples=512, max_backlog_samples=1024,
        retry_after_s=0.5, **kw)
    return serve.ServeRuntime(
        cfg, receiver=_Stub(n_lanes, 256, 64),
        clock=clock if clock is not None else (lambda: 0.0))


def test_admission_queue_and_backpressure():
    with _stub_srv() as srv:
        rs = [srv.connect(f"c{i}") for i in range(6)]
        assert [r.admitted for r in rs] == [True, True, False, False,
                                           False, False]
        assert [r.queued for r in rs] == [False, False, True, True,
                                          False, False]
        # the reject is explicit, reasoned, and carries a
        # deterministic retry hint scaled by the queue depth — with
        # per-session hashed jitter (ISSUE 14: synchronized rejects
        # must not re-arrive in lockstep); the envelope is
        # [0.5, 1.0) x base x (1 + depth)
        assert rs[4].reason == "queue_full"
        assert 0.5 * (0.5 * 3) <= rs[4].retry_after_s < 0.5 * 3
        assert rs[4].retry_after_s != rs[5].retry_after_s
        assert srv.connect("c0").reason == "duplicate"
        st = srv.stats()
        assert (st.admitted, st.queued, st.rejected_admissions) \
            == (2, 2, 2)


def test_ingress_bounds_and_named_errors():
    with _stub_srv() as srv:
        srv.connect("a")
        r = srv.submit("a", np.zeros((600, 2), np.float32))
        assert not r.accepted and r.reason == "oversized"
        ok = np.zeros((128, 2), np.float32)
        for _ in range(8):
            assert srv.submit("a", ok).accepted
        r = srv.submit("a", ok)
        assert not r.accepted and r.reason == "backlog_full" \
            and r.retry_after_s > 0
        with pytest.raises(KeyError, match="known sessions.*'a'"):
            srv.submit("nobody", ok)
        with pytest.raises(ValueError, match="'a'.*\\(n, 2\\)"):
            srv.submit("a", np.zeros((4, 3)))
        assert srv.stats().rejected_slabs == 2


def test_deadline_shed_is_deterministic_and_attributed():
    clock = [0.0]
    with _stub_srv(clock=lambda: clock[0]) as srv:
        srv.connect("fast", slo_s=100.0)
        srv.connect("slow", slo_s=5.0)
        srv.connect("queued-slow", slo_s=5.0)      # waits in queue
        clock[0] = 6.0
        srv.step()
        st = srv.stats()
        assert st.shed == 2 and st.active_sessions == 1
        assert {(s, r) for s, r, _t in st.shed_log} == {
            ("slow", "deadline"), ("queued-slow", "deadline_queued")}
        assert [t for _s, _r, t in st.shed_log] == [6.0, 6.0]
        # a shed session's submit gets its terminal reason, not a
        # crash and not silence
        r = srv.submit("slow", np.zeros((8, 2), np.float32))
        assert not r.accepted and r.reason == "shed:deadline"
        # replay: the same clock sequence sheds identically
    clock2 = [0.0]
    with _stub_srv(clock=lambda: clock2[0]) as srv2:
        srv2.connect("fast", slo_s=100.0)
        srv2.connect("slow", slo_s=5.0)
        srv2.connect("queued-slow", slo_s=5.0)
        clock2[0] = 6.0
        srv2.step()
        assert srv2.stats().shed_log == st.shed_log


def test_drain_accounting_and_scrape():
    with _stub_srv() as srv:
        srv.connect("a")
        srv.connect("b")
        srv.connect("q1")                       # queued
        srv.submit("a", np.zeros((300, 2), np.float32))
        srv.step()
        srv.drain()
        st = srv.stats()
        assert st.active_sessions == 0 and st.queue_depth == 0
        assert srv._rx.flushed
        # q1 was promoted when... no lane freed before drain: it is
        # shed with reason "draining", attributably
        assert ("q1", "draining") in {(s, r)
                                      for s, r, _t in st.shed_log}
        assert st.admitted == st.closed == 2
        assert srv.connect("late").reason == "draining"
        srv.drain()                             # idempotent
        with pytest.raises(RuntimeError, match="after drain"):
            srv.step()
        page = srv.scrape()
        assert "# TYPE serve_admitted counter" in page
        assert 'serve_shed{reason="draining"}' in page
        assert "serve_chunk_seconds" in page


def test_rejected_reconnect_keeps_terminal_reason():
    # a shed session whose reconnect is REJECTED (queue full) must
    # keep answering submits with its terminal reason — the rejected
    # connect must not erase the _gone record and turn the next
    # submit into a KeyError
    clock = [0.0]
    with _stub_srv(clock=lambda: clock[0]) as srv:
        srv.connect("doomed", slo_s=1.0)
        srv.connect("a")
        clock[0] = 2.0
        srv.step()                          # sheds "doomed"
        srv.connect("b")                    # takes the freed lane
        srv.connect("q1")
        srv.connect("q2")                   # queue now full (cap 2)
        r = srv.connect("doomed")
        assert not r.admitted and not r.queued \
            and r.reason == "queue_full"
        r = srv.submit("doomed", np.zeros((8, 2), np.float32))
        assert not r.accepted and r.reason == "shed:deadline"


def test_queued_close_evict_keep_accounting_balance():
    with _stub_srv() as srv:
        srv.connect("a")
        srv.connect("b")
        srv.connect("q-close")              # queued
        srv.connect("q-evict")              # queued
        srv.close("q-close")
        blob, ems, _staged = srv.evict("q-evict")
        assert blob is None and ems == []
        st = srv.stats()
        # the queued terminations ride their own counters: the
        # admitted balance never counts a session it never admitted
        assert st.admitted == 2 and st.closed == 0 and st.evicted == 0
        assert srv._counter_total("serve.closed_queued") == 1
        assert srv._counter_total("serve.evicted_queued") == 1
        srv.drain()
        st = srv.stats()
        assert st.admitted == st.closed == 2


def test_flood_budget_one_chunk_per_tick():
    # the continuous-batching rate limit: one tick moves at most one
    # chunk of a flooding client, the excess stays staged
    with _stub_srv() as srv:
        srv.connect("flood")
        srv.submit("flood", np.zeros((500, 2), np.float32))
        srv.step()
        # chunk_len=256: exactly one chunk's worth moved, 244 staged
        assert srv._sessions["flood"].staged_samples == 500 - 256
        assert srv._rx.stats.chunk_steps == 1
        srv.step()
        assert srv._sessions["flood"].staged_samples == 0


def test_stub_evict_restore_and_lane_recycle():
    with _stub_srv() as srv:
        srv.connect("a")
        srv.submit("a", np.zeros((100, 2), np.float32))
        blob, _ems, staged = srv.evict("a")
        assert blob == b"blob" and len(staged) == 1
        assert not srv.is_active("a")
        r = srv.connect("a", checkpoint=blob)
        assert r.admitted and srv._rx.restored[0] == b"blob"
        st = srv.stats()
        assert st.evicted == 1 and st.restored == 1


# -------------------------------------------------- real-fleet corpus


@pytest.fixture(scope="module")
def corpus():
    """Ten sessions' worth of mixed-rate streams with seeded ragged
    arrival schedules, each stream's lone-receiver oracle, and one
    clean serve pass over S=8 lanes under dispatch counters — the
    fixture every fleet test replays against."""
    clients = serve.synth_load(10, 2, n_bytes=N_BYTES, snr_db=30.0,
                               seed=20260804, tail=FRAME_LEN)
    oracle = {}
    for c in clients:
        oracle[c.sid], _ = framebatch.receive_stream(c.stream, **GEO)
        assert len(oracle[c.sid]) == 2
    cfg = serve.ServeConfig(n_lanes=S, queue_cap=10, sanitize=True,
                            **GEO)
    with dispatch.count_dispatches() as d:
        with serve.ServeRuntime(cfg) as srv:
            frames = serve.run_clients(srv, clients)
            stats = srv.stats()
    return clients, oracle, frames, stats, d, srv


def test_serve_healthy_sessions_bit_identical(corpus):
    # THE serving contract: every session's frames — multiplexed,
    # queued, lane-recycled — equal what a lone single-stream
    # receiver (and hence per-capture rx.receive) emits
    clients, oracle, frames, _st, _d, _srv = corpus
    for c in clients:
        _identical(frames[c.sid], oracle[c.sid])


def test_serve_accounting_balances(corpus):
    clients, oracle, frames, st, _d, _srv = corpus
    assert st.admitted == 10 and st.closed == 10
    assert st.shed == st.evicted == 0
    assert st.frames == sum(len(v) for v in oracle.values()) == 20
    assert st.active_sessions == 0 and st.queue_depth == 0
    # 10 sessions over 8 lanes: at least two waited in the queue
    assert st.queued >= 2


def test_serve_dispatch_budget_under_churn(corpus):
    # ≤ 2 dispatches per chunk-step independent of session count,
    # across admission/queue/close churn — and zero recompiles: the
    # fixed (S, K, chunk) geometry is the whole point
    clients, _oracle, _frames, st, d, _srv = corpus
    assert d.total <= 2 * st.chunk_steps, (dict(d.counts), st)
    from ziria_tpu.phy.wifi import rx as _rx
    cfg = serve.ServeConfig(n_lanes=S, queue_cap=10, sanitize=True,
                            **GEO)
    with dispatch.no_recompile(_rx._jit_stream_chunk_multi,
                               _rx._jit_stream_decode_multi):
        with serve.ServeRuntime(cfg) as srv:
            serve.run_clients(srv, clients)


def test_serve_chunk_latency_histogram_reports(corpus):
    *_x, srv = corpus
    lat = srv.registry.find("serve.chunk_seconds")
    assert lat is not None and lat.count >= 1
    s = lat.summary(scale=1e3)
    assert s["p50"] > 0 and s["p99"] >= s["p50"]
    # the scrape page carries the SLO series
    assert "serve_chunk_seconds_bucket" in srv.scrape()


def test_serve_evict_restore_bit_identical(corpus):
    """The acceptance round trip: a session checkpointed mid-stream
    by the server and restored into a fresh lane emits the same
    remaining frames as the never-evicted run."""
    clients, oracle, _frames, _st, _d, _srv = corpus
    a, b = clients[0], clients[1]
    cfg = serve.ServeConfig(n_lanes=2, queue_cap=4, sanitize=True,
                            **GEO)
    got = {a.sid: [], b.sid: []}
    with serve.ServeRuntime(cfg) as srv:
        srv.connect(a.sid)
        srv.connect(b.sid)
        cut = a.stream.shape[0] // 2
        for lo in range(0, cut, 1500):
            srv.submit(a.sid, a.stream[lo: min(lo + 1500, cut)])
            for sid, f in srv.step():
                got[sid].append(f)
        blob, ems, staged = srv.evict(a.sid)
        for sid, f in ems:
            got[sid].append(f)
        r = srv.connect(a.sid, checkpoint=blob)
        assert r.admitted
        for s_ in staged:
            srv.submit(a.sid, s_)
        srv.submit(a.sid, a.stream[cut:])
        srv.submit(b.sid, b.stream)
        for _ in range(4):
            for sid, f in srv.step():
                got[sid].append(f)
        for sid, f in srv.drain():
            got[sid].append(f)
        st = srv.stats()
    _identical(got[a.sid], oracle[a.sid])
    _identical(got[b.sid], oracle[b.sid])       # lane-mate untouched
    assert st.evicted == 1 and st.restored == 1


def test_serve_nan_client_quarantined_not_lanemates(corpus):
    """One poisoned client never degrades its lane-mates: the NaN
    session quarantines (drops, never garbage), every other session
    stays bit-identical."""
    clients, oracle, _frames, _st, _d, _srv = corpus
    bad = serve.synth_load(4, 2, n_bytes=N_BYTES, snr_db=30.0,
                           seed=20260804, tail=FRAME_LEN,
                           misbehave={1: "nan"})
    cfg = serve.ServeConfig(n_lanes=4, queue_cap=4, sanitize=True,
                            **GEO)
    with serve.ServeRuntime(cfg) as srv:
        frames = serve.run_clients(srv, bad)
    for c in bad:
        if c.mode == "nan":
            by_start = {f.start: f for f in oracle[c.sid]}
            for f in frames[c.sid]:
                assert f.start in by_start and _same(
                    f, by_start[f.start])
        else:
            _identical(frames[c.sid], oracle[c.sid])


def test_serve_chaos_zero_crashes_identical(corpus):
    """Transient dispatch faults during a serve run: retried through
    the guarded path, every session still bit-identical, zero
    crashes."""
    clients, oracle, _frames, _st, _d, _srv = corpus
    sub = clients[:4]
    cfg = serve.ServeConfig(n_lanes=4, queue_cap=4, sanitize=True,
                            **GEO)
    with faults.inject(
            faults.FaultSpec("rx.stream_chunk_multi", "transient",
                             every=3),
            faults.FaultSpec("rx.stream_decode_multi", "transient",
                             every=2), seed=5) as plan:
        with serve.ServeRuntime(cfg) as srv:
            frames = serve.run_clients(srv, sub)
    assert plan.total_fired > 0
    for c in sub:
        _identical(frames[c.sid], oracle[c.sid])


def test_serve_restore_refuses_geometry_mismatch(corpus):
    clients, *_ = corpus
    sr = framebatch.StreamReceiver(**GEO)
    sr.push(clients[0].stream[: CHUNK // 2])
    blob, _ = sr.checkpoint()
    msr = framebatch.MultiStreamReceiver(2, chunk_len=2 * CHUNK,
                                         frame_len=FRAME_LEN,
                                         max_frames_per_chunk=K,
                                         check_fcs=True)
    with pytest.raises(resilience.CarryCheckpointError,
                       match="geometry mismatch"):
        msr.restore_stream(0, blob)
    with pytest.raises(resilience.CarryCheckpointError):
        msr.restore_stream(1, b"garbage")


# ------------------------------------------- satellites: ids, arrivals


def test_unknown_stream_ids_name_the_known_ids():
    msr = framebatch.MultiStreamReceiver(4, **GEO)
    for exc, call in (
            (IndexError, lambda: msr.push(7, np.zeros((4, 2)))),
            (IndexError, lambda: msr.push(-1, np.zeros((4, 2)))),
            (KeyError, lambda: msr.push_many({9: np.zeros((4, 2))})),
            (IndexError, lambda: msr.checkpoint(4)),
            (IndexError, lambda: msr.carry(11)),
            (IndexError, lambda: msr.quarantined(5)),
            (IndexError, lambda: msr.flush_stream(4)),
            (IndexError, lambda: msr.reset_stream(-2)),
            (IndexError, lambda: msr.restore_stream(6, b"x"))):
        with pytest.raises(exc, match=r"known\s+ids are 0\.\.3"):
            call()


def test_arrival_schedules_seeded_exact_and_backcompat():
    psdus = [[np.arange(N_BYTES, dtype=np.uint8)] for _ in range(2)]
    rates = [[6], [54]]
    # default: the two-element return, unchanged call sites
    out = link.stream_many_multi(psdus, rates, seed=3, add_fcs=True,
                                 snr_db=30.0, tail=FRAME_LEN)
    assert len(out) == 2
    streams, starts = out
    # arrival spec: third element, slabs concatenate back EXACTLY
    spec = link.ArrivalSpec(slab_lo=200, slab_hi=900, gap_lo=0,
                            gap_hi=2)
    s2, st2, scheds = link.stream_many_multi(
        psdus, rates, seed=3, add_fcs=True, snr_db=30.0,
        tail=FRAME_LEN, arrival=spec)
    assert all(np.array_equal(a, b) for a, b in zip(streams, s2))
    for i, sched in enumerate(scheds):
        ticks = [t for t, _s in sched]
        assert ticks == sorted(ticks)
        assert all(200 <= s.shape[0] < 900 for _t, s in sched[:-1])
        cat = np.concatenate([s for _t, s in sched])
        assert np.array_equal(cat, s2[i])
    # seeded-deterministic: same seed, same schedule
    _s3, _st3, scheds3 = link.stream_many_multi(
        psdus, rates, seed=3, add_fcs=True, snr_db=30.0,
        tail=FRAME_LEN, arrival=spec)
    for a, b in zip(scheds, scheds3):
        assert [t for t, _ in a] == [t for t, _ in b]
        assert all(np.array_equal(x, y)
                   for (_t, x), (_u, y) in zip(a, b))
    with pytest.raises(ValueError, match="slab range"):
        link.arrival_schedule(streams[0],
                              link.ArrivalSpec(slab_lo=0), 1)


def test_pushing_a_schedule_equals_pushing_the_stream():
    # push-boundary invariance through the REAL receiver: the ragged
    # slab schedule emits bit-identically to the whole-stream push
    rng = np.random.default_rng(11)
    psdus = [[rng.integers(0, 256, N_BYTES).astype(np.uint8)
              for _ in range(2)]]
    _s, _t, scheds = link.stream_many_multi(
        psdus, [[24, 54]], seed=7, add_fcs=True, snr_db=30.0,
        tail=FRAME_LEN, arrival=link.ArrivalSpec())
    stream = np.concatenate([s for _t, s in scheds[0]])
    want, _ = framebatch.receive_stream(stream, **GEO)
    sr = framebatch.StreamReceiver(**GEO)
    got = []
    for _t, slab in scheds[0]:
        got += sr.push(slab)
    got += sr.flush()
    _identical(got, want)
