"""Pallas Viterbi kernel vs the lax.scan reference implementation.

Runs the kernels in Pallas interpret mode on CPU (conftest pins the CPU
backend); on real TPU the same code path compiles via Mosaic.
"""

import numpy as np
import pytest

from ziria_tpu.ops import coding, viterbi, viterbi_pallas


def _noisy_llrs(rng, n_bits, snr=2.0):
    bits = rng.integers(0, 2, n_bits).astype(np.uint8)
    coded = np.asarray(coding.np_conv_encode_ref(bits), np.float32)
    llr = (2.0 * coded - 1.0) * snr + rng.normal(0, 1.0, coded.size)
    return bits, llr.astype(np.float32).reshape(-1, 2)


def test_matches_scan_reference_hard():
    rng = np.random.default_rng(0)
    B, n = 5, 96
    msgs, llrs = [], []
    for _ in range(B):
        bits = rng.integers(0, 2, n).astype(np.uint8)
        bits[-coding.K + 1:] = 0  # zero-tail termination
        coded = np.asarray(coding.np_conv_encode_ref(bits), np.float32)
        msgs.append(bits)
        llrs.append((2.0 * coded - 1.0).reshape(-1, 2))
    llrs = np.stack(llrs)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs))
    assert got.shape == (B, n)
    for k in range(B):
        np.testing.assert_array_equal(got[k], msgs[k])


def test_matches_scan_reference_soft():
    rng = np.random.default_rng(1)
    B, n = 4, 120
    llrs = np.stack([_noisy_llrs(rng, n)[1] for _ in range(B)])
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs))
    for k in range(B):
        want = np.asarray(viterbi.viterbi_decode(llrs[k]))
        np.testing.assert_array_equal(got[k], want)


def test_lane_padding_and_nbits():
    rng = np.random.default_rng(2)
    B, n = 3, 64  # B far below one 128-lane tile
    llrs = np.stack([_noisy_llrs(rng, n)[1] for _ in range(B)])
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs, n_bits=50))
    assert got.shape == (B, 50)
    want = np.stack(
        [np.asarray(viterbi.viterbi_decode(llrs[k], n_bits=50))
         for k in range(B)])
    np.testing.assert_array_equal(got, want)


def test_flat_llr_layout():
    rng = np.random.default_rng(3)
    _, llr = _noisy_llrs(rng, 80)
    flat = llr.reshape(1, -1)
    a = np.asarray(viterbi_pallas.viterbi_decode_batch(flat))
    b = np.asarray(viterbi_pallas.viterbi_decode_batch(llr[None]))
    np.testing.assert_array_equal(a, b)


def test_multi_tile_batch():
    rng = np.random.default_rng(4)
    B, n = 130, 40  # > 128 forces two lane tiles
    msgs, llrs = [], []
    for _ in range(B):
        bits = rng.integers(0, 2, n).astype(np.uint8)
        bits[-coding.K + 1:] = 0
        coded = np.asarray(coding.np_conv_encode_ref(bits), np.float32)
        msgs.append(bits)
        llrs.append((2.0 * coded - 1.0).reshape(-1, 2))
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(np.stack(llrs)))
    np.testing.assert_array_equal(got, np.stack(msgs))
