"""Seeded fuzzing of the PARALLEL seams (VERDICT r2 #7): random
pipelines wrapped in sp (stream split), pp (stage pipeline), dp x sp
(batched streams), and the chunked-loop hybrid path, each required to
equal the single-chip execution exactly. The discipline that caught
the uint8 C-promotion bug, pointed at the sharding boundaries.

All runs use the 8-device virtual CPU mesh from tests/conftest.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.parallel.stages import lower_stage_parallel
from ziria_tpu.parallel.streampar import (stream_mesh, stream_parallel,
                                          stream_parallel_batched)

N_SP = 24
N_PP = 16
N_DPSP = 8
N_CHUNK = 24


# ------------------------------------------------------------ stage gen


def _gen_stage(rng, kind_pool):
    """One random lowerable stage (int32 items in/out)."""
    kind = rng.choice(kind_pool)
    a = int(rng.integers(0, 1000))
    b = int(rng.integers(1, 7))
    if kind == "affine":
        return z.zmap(lambda x, _a=a, _b=b: x * _b + _a,
                      name=f"aff{b}_{a}")
    if kind == "mod":
        m = int(rng.choice([17, 251, 4093]))
        return z.zmap(lambda x, _m=m: x % _m, name=f"mod{m}")
    if kind == "pairsum":
        return z.zmap(lambda v: jnp.sum(v, axis=0), in_arity=2,
                      out_arity=1, name="pairsum")
    if kind == "dup":
        return z.zmap(lambda x: jnp.stack([x, x + 1]), in_arity=1,
                      out_arity=2, name="dup")
    if kind == "counter":
        s0 = int(rng.integers(0, 5))
        return z.map_accum(
            lambda s, x: (s + 1, x + s), s0, name=f"ctr{s0}",
            advance=lambda s, n: s + n)
    if kind == "window":
        w = int(rng.choice([2, 3, 4]))
        taps = jnp.asarray(
            rng.integers(-3, 4, size=w).astype(np.int32))

        def step(state, x, _t=taps):
            state = jnp.concatenate([state[1:], x[None]])
            return state, jnp.sum(state * _t)

        return z.map_accum(step, jnp.zeros(w, jnp.int32),
                           name=f"win{w}", memory=w)
    raise AssertionError(kind)


def _gen_pipeline(rng, n, kind_pool):
    return z.pipe(*[_gen_stage(rng, kind_pool) for _ in range(n)])


# ------------------------------------------------------------ sp


@pytest.mark.parametrize("seed", range(N_SP))
def test_fuzz_sp_equals_single_chip(seed):
    rng = np.random.default_rng(1000 + seed)
    pool = ["affine", "mod", "pairsum", "dup", "counter", "window"]
    prog = _gen_pipeline(rng, int(rng.integers(1, 4)), pool)
    n = int(rng.integers(50, 3000))
    xs = rng.integers(-1000, 1000, size=n).astype(np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, stream_mesh(8))
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(want),
        err_msg=f"seed {seed}: {[s.label() for s in ir.pipeline_stages(prog)]}")


# ------------------------------------------------------------ pp


@pytest.mark.parametrize("seed", range(N_PP))
def test_fuzz_pp_equals_fused(seed):
    rng = np.random.default_rng(2000 + seed)
    K = int(rng.choice([2, 4]))
    pool = ["affine", "mod", "pairsum", "dup", "counter", "window"]
    segs = [_gen_stage(rng, pool) for _ in range(K)]
    comp = z.par_pipe(*segs)
    mesh = Mesh(np.array(jax.devices()[:K]), ("pp",))
    pp = lower_stage_parallel(comp, mesh, width=int(rng.choice([1, 3])),
                              in_item=jax.ShapeDtypeStruct((),
                                                           jnp.int32))
    M = int(rng.integers(1, 7))
    r = int(rng.integers(0, pp.take))          # ragged remainder
    n = M * pp.take + r
    xs = rng.integers(-1000, 1000, size=n).astype(np.int32)
    seq = z.pipe(*segs)
    want = run_jit(seq, xs)

    from ziria_tpu.backend.execute import run_jit_carry
    ys, carry = pp.run_carry(
        xs[: M * pp.take].reshape(M, pp.take))
    parts = [np.asarray(ys).reshape(-1)]
    tail, _ = run_jit_carry(seq, xs[M * pp.take:], carry=carry, width=1)
    parts.append(np.asarray(tail).reshape(-1))
    got = np.concatenate(parts)
    np.testing.assert_array_equal(
        got, np.asarray(want).reshape(-1),
        err_msg=f"seed {seed}: {pp.labels} take={pp.take} M={M} r={r}")


# ------------------------------------------------------------ dp x sp


@pytest.mark.parametrize("seed", range(N_DPSP))
def test_fuzz_dp_x_sp_equals_per_frame(seed):
    rng = np.random.default_rng(3000 + seed)
    pool = ["affine", "mod", "counter", "window"]
    prog = _gen_pipeline(rng, int(rng.integers(1, 4)), pool)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("dp", "sp"))
    width = int(rng.choice([4, 16]))
    # aligned layout: items = sp * width * take (take is 1 for this
    # pool), frames % dp == 0
    B = int(rng.choice([2, 4]))
    N = 4 * width * int(rng.integers(1, 5))
    if rng.random() < 0.5:
        # ragged (r4): lengths off the sp*width grid exercise the
        # bulk + per-frame carry-seeded host tail split
        N += int(rng.integers(1, 4 * width))
    batch = rng.integers(-1000, 1000, size=(B, N)).astype(np.int32)
    got = stream_parallel_batched(prog, batch, mesh, width=width)
    for f in range(B):
        want = run_jit(prog, batch[f], width=width)
        np.testing.assert_array_equal(
            np.asarray(got[f]), np.asarray(want),
            err_msg=f"seed {seed} frame {f}")


# ------------------------------------------------------------ chunked


def _gen_chunk_program(rng):
    """Surface program with stream-control loops (the chunked-machine
    shapes): takes/emits under data-dependent branches inside times
    loops, plus a detect-style while."""
    n_iter = int(rng.integers(40, 200))
    lead = int(rng.integers(0, 30))
    th = int(rng.integers(50, 5000))
    body = []
    body.append(f"""
  var s : int32 := 0;
  var g : int32 := 0;
  var armed : bool := false;
  while (!armed) {{
    x <- take;
    do {{
      s := s + x * x - (s / 5);
      if (s % 10000 > {th}) then {{ armed := true }};
      g := g + 1
    }}
  }};
  emit s;
  times {n_iter} {{
    var v : int32 := 0;
    if (g < {lead + 40}) then {{ do {{ v := g * 3 }} }}
    else {{ y <- take; do {{ v := y + s }} }};
    do {{
      g := g + 1;
      if (v % 2 == 0) then {{ s := s + v }} else {{ s := s - v }}
    }}
  }};
  emit s;
  times {int(rng.integers(2, 5))} {{ emit g; do {{ g := g + 7 }} }}""")
    src = ("let comp main = read[int32] >>> {" + "".join(body)
           + "\n} >>> write[int32]\n")
    n = int(rng.integers(100, 400))
    xs = rng.integers(-500, 500, size=n).astype(np.int32)
    return src, xs


@pytest.mark.parametrize("seed", range(N_CHUNK))
def test_fuzz_chunked_loops_equal_oracle(seed):
    from ziria_tpu.backend import hybrid as H
    from ziria_tpu.frontend import compile_source
    from ziria_tpu.interp.interp import run

    rng = np.random.default_rng(4000 + seed)
    src, xs = _gen_chunk_program(rng)
    prog = compile_source(src)
    want = run(prog.comp, list(xs))
    got = run(H.hybridize(prog.comp), list(xs))
    np.testing.assert_array_equal(
        np.asarray(want.out_array()), np.asarray(got.out_array()),
        err_msg=f"seed {seed}\n{src}")
    assert want.terminated_by == got.terminated_by, f"seed {seed}"


# ------------------------------------------------------------ framebatch


N_FRAMEBATCH = 10


@pytest.mark.parametrize("seed", range(N_FRAMEBATCH))
def test_fuzz_framebatch_equals_per_frame(seed):
    """Random chunked-machine programs over random RAGGED frame sets:
    run_many (threads + shared StepBatcher + vmapped steps) must be
    bit-identical to running every frame alone — the seam where lane
    masking, regrouping, pushback, and interpreter tails all meet."""
    from ziria_tpu.backend import hybrid as H
    from ziria_tpu.backend.framebatch import StepBatcher, run_many
    from ziria_tpu.frontend import compile_source
    from ziria_tpu.interp.interp import run

    rng = np.random.default_rng(6000 + seed)
    src, _xs = _gen_chunk_program(rng)
    hyb = H.hybridize(compile_source(src).comp)
    n_frames = int(rng.integers(2, 7))
    frames = [rng.integers(-500, 500,
                           size=int(rng.integers(30, 400))).astype(
                               np.int32)
              for _ in range(n_frames)]
    want = [run(hyb, list(f)) for f in frames]
    got = run_many(hyb, frames, batcher=StepBatcher(n_frames))
    for k, (w, g) in enumerate(zip(want, got)):
        np.testing.assert_array_equal(
            np.asarray(w.out_array()), np.asarray(g.out_array()),
            err_msg=f"seed {seed} frame {k}\n{src}")
        assert w.terminated_by == g.terminated_by, f"seed {seed}:{k}"
