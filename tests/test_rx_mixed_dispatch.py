"""Mixed-rate one-dispatch DATA decode (phy/wifi/rx.decode_data_mixed
+ backend/framebatch.receive_many): a batch with ALL EIGHT rates
present decodes through ONE jitted ``lax.switch`` dispatch,
bit-identical to the host-side bucketed path, with the DATA-stage
compile count dropping from O(rates x log lengths) to O(log lengths).

The expensive geometry compiles happen ONCE in the module fixture;
the corpus length is chosen so every test's common symbol bucket hits
the same compiled dispatch. Compile counts are measured with
`utils.dispatch.cache_growth` — lru_cache DELTAS, never cache_clear:
this module runs inside the full suite, and clearing the shared
bucketed cache would throw away compiled decoders later test files
reuse (the per-rate/bucket entries are process-wide state). The
exact O(rates x log lengths) -> O(log lengths) before/after numbers
are the bench artifact's job (tools/rx_dispatch_bench.py, which owns
clean caches in its own process); here the contract is the
cache-growth SHAPE.
"""

import numpy as np
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import RATES
from ziria_tpu.utils.bits import bytes_to_bits
from ziria_tpu.utils.dispatch import cache_growth

N_BYTES = 16   # small corpus: 8-symbol common bucket keeps the
               # interpret-mode Pallas compiles inside the tier-1 budget


def _capture(rng, mbps, n_bytes):
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    s = np.asarray(tx.encode_frame(psdu, mbps))
    cap = np.concatenate([np.zeros((50, 2), np.float32), s], axis=0)
    return cap, np.asarray(bytes_to_bits(psdu))


@pytest.fixture(scope="module")
def corpus():
    """All-8-rates corpus + reference results + the compile-count
    DELTAS (cache growth while decoding the corpus, measured without
    clearing the suite-shared caches)."""
    rng = np.random.default_rng(20260802)
    caps, wants = [], []
    for m in sorted(RATES):
        c, w = _capture(rng, m, N_BYTES)
        caps.append(c)
        wants.append(w)
    with cache_growth(rx._jit_decode_data_mixed) as gm:
        mixed = framebatch.receive_many(caps)
    with cache_growth(rx._jit_decode_data_bucketed) as gb:
        bucketed = [rx.receive(c) for c in caps]
    return (caps, wants, bucketed, mixed, gb.total, gm.total)


def test_all_8_rates_bit_identical_to_bucketed(corpus):
    caps, wants, bucketed, mixed, _cb, _cm = corpus
    assert [r.rate_mbps for r in mixed] == sorted(RATES)
    for b, g, w in zip(bucketed, mixed, wants):
        assert b.ok and g.ok
        assert g.length_bytes == N_BYTES
        np.testing.assert_array_equal(g.psdu_bits, w)
        np.testing.assert_array_equal(g.psdu_bits, b.psdu_bits)


def test_one_jitted_switch_serves_every_rate(corpus):
    _caps, _wants, _bucketed, _mixed, cb, cm = corpus
    # the DATA stage of the whole mixed batch is ONE compiled callable
    # (one symbol bucket here): the mixed cache grew by AT MOST one
    # entry for all 8 rates (zero when an earlier file — the batched-
    # acquire suite shares this geometry on purpose — already built
    # the same key), where the bucketed path grows one entry per
    # UNSEEN (rate, bucket) pair — up to 8 here (the shared-cache
    # economics the mixed dispatch exists to beat)
    assert cm <= 1
    assert cb <= len(RATES)


def test_mixed_int16_metric_rides_the_same_dispatch(corpus):
    caps, wants, _bucketed, _mixed, _cb, _cm = corpus
    got = framebatch.receive_many(caps, viterbi_metric="int16")
    for g, w in zip(got, wants):
        assert g.ok
        np.testing.assert_array_equal(g.psdu_bits, w)


def test_failed_lanes_keep_positions(corpus):
    # a lane that fails acquisition keeps its position and never
    # reaches the device batch. 7 live lanes pad back to the
    # fixture's 8-lane geometry, so this reuses the compiled dispatch
    # (a fresh lane count would be a fresh — expensive — compile);
    # the noise lane stays under the fixture's 1024-sample capture
    # bucket so the batched-acquire graph is reused too.
    caps, wants, _bucketed, _mixed, _cb, _cm = corpus
    rng = np.random.default_rng(3)
    noise = rng.normal(scale=0.01, size=(1000, 2)).astype(np.float32)
    lanes = [caps[0], noise] + caps[2:]
    got = framebatch.receive_many(lanes)
    assert got[0].ok and not got[1].ok
    np.testing.assert_array_equal(got[0].psdu_bits, wants[0])
    for g, w in zip(got[2:], wants[2:]):
        assert g.ok
        np.testing.assert_array_equal(g.psdu_bits, w)


def test_mixed_lengths_share_one_bucket(corpus):
    # different PSDU lengths (different true symbol counts) pad to ONE
    # common bucket: shorter lanes ride pad symbols, not a second
    # dispatch — bits still exact per lane. Lengths are chosen so the
    # common bucket equals the fixture corpus's (the 6 Mbps lane's
    # 8-symbol bucket dominates), hitting the already-compiled
    # dispatch.
    caps, wants, _bucketed, _mixed, _cb, _cm = corpus
    rng = np.random.default_rng(8)
    c54, w54 = _capture(rng, 54, 120)     # 5 syms: same 8-sym bucket
    with cache_growth(rx._jit_decode_data_mixed) as g:
        got = framebatch.receive_many(caps[:7] + [c54])
    for r, (m, nb, w) in zip(
            got, [(mm, N_BYTES, ww) for mm, ww
                  in zip(sorted(RATES)[:7], wants[:7])]
            + [(54, 120, w54)]):
        assert r.ok and r.rate_mbps == m and r.length_bytes == nb
        np.testing.assert_array_equal(r.psdu_bits, w)
    assert g.total == 0


def test_rate_index_order_is_the_switch_order():
    # decode_data_mixed's branches are built in RATE_MBPS_ORDER; the
    # index map every caller uses must agree, or a lane would decode
    # at the wrong rate (the e2e identity above would catch it late —
    # this pins the contract directly and costs nothing)
    assert rx.RATE_MBPS_ORDER == tuple(sorted(RATES))
    for i, m in enumerate(rx.RATE_MBPS_ORDER):
        assert rx.RATE_INDEX[m] == i
    assert rx.MAX_DBPS == max(p.n_dbps for p in RATES.values())
