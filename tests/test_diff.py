import numpy as np
from ziria_tpu.utils.diff import stream_diff

def test_bool_vs_float_symmetric():
    a = np.array([True]); b = np.array([0.9])
    r1 = stream_diff(a, b, atol=0.2)
    r2 = stream_diff(b, a, atol=0.2)
    assert bool(r1) == bool(r2)  # both tolerance path
    assert r1.ok and r2.ok

def test_bool_bool_exact():
    assert not stream_diff(np.array([True]), np.array([False]), atol=9.0)

def test_int_exact_despite_tolerance():
    assert not stream_diff(np.array([1]), np.array([2]), atol=9.0)
