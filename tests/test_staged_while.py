"""Dynamic (data-dependent) `while` under the jit backend: staged as
`lax.while_loop` over the mutable cells in scope (eval._staged_while).
Round 1 confined dynamic while to the interpreter; the reference
compiles it to a C while loop (SURVEY.md §0 statement forms), so the
flag-matrix discipline now applies to it too."""

import numpy as np
import pytest

from ziria_tpu.backend.execute import run_jit
from ziria_tpu.frontend import compile_source
from ziria_tpu.frontend.eval import ZiriaRuntimeError
from ziria_tpu.interp.interp import run


def both(src, xs, **kw):
    prog = compile_source(src, **kw)
    want = run(prog.comp, list(np.asarray(xs))).out_array()
    got = np.asarray(run_jit(prog.comp, xs))
    np.testing.assert_array_equal(got, np.asarray(want))
    return got


ILOG = """
fun ilog2(x: int32) : int32 {
  var v: int32 := x;
  var n: int32 := 0;
  while (v > 1) { v := v >> 1; n := n + 1 }
  return n
}
let comp main = read[int32] >>> map ilog2 >>> write[int32]
"""


def test_while_per_item_under_jit():
    xs = np.array([1, 2, 3, 4, 7, 8, 1000, 65536], np.int32)
    got = both(ILOG, xs)
    np.testing.assert_array_equal(got, np.floor(np.log2(xs)).astype(np.int32))


def test_while_traced_condition_in_do_block():
    # collatz step count, bounded: loop state = two stream-level vars
    src = """
    let comp main = read[int32] >>> repeat {
      x <- take;
      var v: int32 := x;
      var n: int32 := 0;
      do {
        while (v != 1 && n < 64) {
          if v % 2 == 0 then { v := v / 2 } else { v := 3 * v + 1 };
          n := n + 1
        }
      };
      emit n
    } >>> write[int32]
    """
    xs = np.array([1, 2, 3, 6, 7, 27], np.int32)
    got = both(src, xs)
    # python oracle
    def collatz(v):
        n = 0
        while v != 1 and n < 64:
            v = v // 2 if v % 2 == 0 else 3 * v + 1
            n += 1
        return n
    np.testing.assert_array_equal(got, [collatz(int(v)) for v in xs])


def test_while_carries_narrow_dtype():
    # int16 counter must stay int16 across iterations (entry-dtype pin)
    src = """
    fun count(x: int16) : int16 {
      var n: int16 := 0;
      var v: int16 := x;
      while (v > 0) { v := v - int16(1); n := n + int16(1) }
      return n
    }
    let comp main = read[int16] >>> map count >>> write[int16]
    """
    xs = np.array([0, 1, 5, 100], np.int16)
    got = both(src, xs)
    np.testing.assert_array_equal(got, xs.clip(min=0))


def test_while_static_prefix_then_traced():
    # the loop runs concretely until the condition becomes traced —
    # staging may start mid-loop and must still agree with the oracle
    src = """
    let comp main = read[int32] >>> repeat {
      x <- take;
      var i: int32 := 0;
      var acc: int32 := 0;
      do {
        while (i < 3 || acc < x) { acc := acc + i; i := i + 1 }
      };
      emit acc
    } >>> write[int32]
    """
    xs = np.array([0, 1, 10, 40], np.int32)
    both(src, xs)


def test_dynamic_bound_for_under_jit():
    # for-loop bounds computed from traced data stage as lax.fori_loop
    # with traced bounds (the reference's C backend compiles these
    # trivially); the interpreter needs them concrete, which they are
    src = """
    fun tri(x: int32) : int32 {
      var acc : int32 := 0;
      var n : int32 := x % 10;
      for i in [0, n] { acc := acc + i }
      return acc
    }
    let comp main = read[int32] >>> map tri >>> write[int32]
    """
    xs = np.array([0, 3, 7, 12, 25, 99], np.int32)
    got = both(src, xs)
    np.testing.assert_array_equal(
        got, [sum(range(int(v) % 10)) for v in xs])


def test_non_scalar_condition_diagnosed():
    # an array-valued condition is a condition bug, not a staging
    # situation — both backends must say so, not misreport carry shapes
    src = """
    fun f(v: arr[4] int32) : int32 {
      var n: int32 := 0;
      while (v > 0) { n := n + 1 }
      return n
    }
    let comp main = read[int32] >>> repeat { x <- takes 4; emit f(x) }
      >>> write[int32]
    """
    prog = compile_source(src)
    xs = np.arange(8, dtype=np.int32)
    with pytest.raises(ZiriaRuntimeError, match="scalar"):
        run(prog.comp, list(xs))
    with pytest.raises(ZiriaRuntimeError, match="scalar"):
        run_jit(prog.comp, xs)


def test_return_inside_dynamic_while_rejected():
    src = """
    fun f(x: int32) : int32 {
      var v: int32 := x;
      while (v > 0) { return v }
      return 0
    }
    let comp main = read[int32] >>> map f >>> write[int32]
    """
    prog = compile_source(src)
    with pytest.raises(ZiriaRuntimeError, match="return inside"):
        run_jit(prog.comp, np.array([1, 2], np.int32))
