"""The declarative Geometry object + autotuner (ISSUE 16): the
default ``Geometry()`` is a NO-OP by construction — zero new compiled
programs and bit-identical emissions against the legacy per-knob
arguments at the suite-shared 4096/1024/K=8 streaming geometry —
while ``resolve()`` folds env knobs exactly once, serialization and
the checkpoint geometry fingerprint round-trip (legacy blobs missing
post-format fields included), and the autotuner pipeline
(cost-prune -> measure -> identity gate -> ledger record ->
``Geometry.tuned()``) runs deterministically under injected fakes.

Budget discipline: every compiled-path test constructs at the SAME
4096/1024/K=8 geometry the streaming/batched-acquire/mixed suites
share, pays its compiles once in a module fixture, and pins the
geometry-object path under ``dispatch.no_recompile`` against it. The
autotuner tests never touch jax at all (fakes).
"""

import dataclasses
import json
import os

import numpy as np
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import link
from ziria_tpu.phy.wifi import rx
from ziria_tpu.runtime import resilience, serve
from ziria_tpu.utils import autotune, dispatch, geometry
from ziria_tpu.utils.geometry import Geometry

N_BYTES = 12
CHUNK, FRAME_LEN, K = 4096, 1024, 8
#: the suite-shared streaming geometry, as a Geometry object
GEO = Geometry(chunk_len=CHUNK, frame_len=FRAME_LEN,
               max_frames_per_chunk=K)
LEGACY_KW = dict(chunk_len=CHUNK, frame_len=FRAME_LEN,
                 max_frames_per_chunk=K, check_fcs=True)


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


# ----------------------------------------------------- the object itself


def test_default_geometry_is_todays_constants():
    # the no-op-by-construction contract rests on these exact values;
    # a drift here silently re-keys every compiled surface
    g = Geometry()
    assert (g.chunk_len, g.frame_len, g.max_frames_per_chunk,
            g.n_streams) == (1 << 13, 2048, 8, 8)
    assert (g.sym_bucket_min, g.capture_bucket_min,
            g.bit_bucket_min) == (4, 512, 128)
    assert (g.threshold, g.min_run, g.dead_zone) == (0.75, 33, 320)
    # decode-mode knobs default to "resolve from env"
    assert g.viterbi_window is None and g.viterbi_metric is None
    assert g.viterbi_radix is None and g.fused_demap is None
    assert g.sco_track is None
    r = g.resolve()      # clean env -> the historical concrete values
    assert (r.viterbi_window, r.viterbi_metric, r.viterbi_radix,
            r.fused_demap, r.sco_track) == (0, "float32", 2, False,
                                            False)
    assert r.resolve() == r                      # idempotent


def test_geometry_is_frozen_and_hashable():
    g = Geometry()
    assert hash(g) == hash(Geometry())
    assert g == Geometry() and g != GEO
    with pytest.raises(dataclasses.FrozenInstanceError):
        g.chunk_len = 1
    d = {g: "default", GEO: "stream"}             # usable as a dict key
    assert d[Geometry()] == "default"


def test_bucket_rules_match_dispatch_pow2(monkeypatch):
    g = Geometry()
    assert g.sym_bucket(3) == 4 and g.sym_bucket(21) == 32
    assert g.capture_bucket(100) == 512
    assert g.capture_bucket(1500) == 2048
    assert g.bit_bucket(1) == 128 and g.bit_bucket(129) == 256
    # the floors are per-instance tunables, not literals
    assert Geometry(sym_bucket_min=16).sym_bucket(3) == 16


def test_resolve_env_precedence_and_scoped_restore(monkeypatch):
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "4")
    monkeypatch.setenv("ZIRIA_VITERBI_WINDOW", "96")
    monkeypatch.setenv("ZIRIA_RX_SCO_TRACK", "1")
    r = Geometry().resolve()
    assert (r.viterbi_radix, r.viterbi_window, r.sco_track) == \
        (4, 96, True)
    # an explicit field beats the env default — CLI args win
    e = Geometry(viterbi_radix=2, viterbi_window=0).resolve()
    assert (e.viterbi_radix, e.viterbi_window) == (2, 0)
    # validation: explicit junk raises with the env var's message
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "3")
    with pytest.raises(ValueError, match="ZIRIA_VITERBI_RADIX"):
        Geometry().resolve()
    with pytest.raises(ValueError, match="viterbi_radix"):
        Geometry(viterbi_radix=7).resolve()
    with pytest.raises(ValueError, match="viterbi_metric"):
        Geometry(viterbi_metric="float64").resolve()
    monkeypatch.delenv("ZIRIA_VITERBI_RADIX")
    monkeypatch.delenv("ZIRIA_VITERBI_WINDOW")
    monkeypatch.delenv("ZIRIA_RX_SCO_TRACK")
    # the monkeypatched reads never leaked into the module: clean env
    # resolves back to the historical defaults (scoped restore)
    r2 = Geometry().resolve()
    assert (r2.viterbi_radix, r2.viterbi_window, r2.sco_track) == \
        (2, 0, False)


def test_serialization_round_trips_strictly():
    r = GEO.replace(viterbi_radix=4).resolve()
    assert Geometry.from_json(r.to_json()) == r
    assert Geometry.from_dict(r.as_dict()) == r
    with pytest.raises(ValueError, match="warp_factor"):
        Geometry.from_dict({"chunk_len": 4096, "warp_factor": 9})


def test_serve_config_defaults_derive_from_geometry():
    # the ISSUE 16 dedupe satellite: ServeConfig's fleet-geometry
    # defaults ARE Geometry's — no second "1 << 13" literal to drift
    c = serve.ServeConfig()
    g = Geometry()
    assert (c.n_lanes, c.chunk_len, c.frame_len,
            c.max_frames_per_chunk) == \
        (g.n_streams, g.chunk_len, g.frame_len, g.max_frames_per_chunk)
    t = serve.ServeConfig.from_geometry(
        g.replace(chunk_len=16384, n_streams=4), queue_cap=3)
    assert (t.n_lanes, t.chunk_len, t.queue_cap) == (4, 16384, 3)
    assert t.frame_len == g.frame_len


# ------------------------------------------- compiled-surface no-op pin


@pytest.fixture(scope="module")
def corpus():
    """One stream at the suite-shared geometry, decoded ONCE with the
    legacy per-knob arguments (paying whatever compiles this process
    still needs) — the oracle every geometry-object path must match
    without compiling anything new."""
    from ziria_tpu.phy.wifi.params import RATES

    rng = np.random.default_rng(20260806)
    mbps = sorted(RATES)[:4]
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in mbps]
    stream, starts = link.stream_many(
        psdus, mbps, snr_db=30.0, cfo=1e-4, delay=60, seed=5,
        add_fcs=True, tail=FRAME_LEN)
    got_legacy, _ = framebatch.receive_stream(stream, streaming=True,
                                              **LEGACY_KW)
    return stream, starts, got_legacy


def test_default_geometry_compiles_nothing_new(corpus):
    """THE tentpole pin: a receiver built from the Geometry object at
    the already-compiled geometry adds ZERO programs to any streaming
    cache and emits bit-identical frames."""
    stream, starts, got_legacy = corpus
    with dispatch.no_recompile(rx._jit_stream_chunk,
                               rx._jit_stream_decode):
        got_geo, _ = framebatch.receive_stream(
            stream, streaming=True, check_fcs=True, geometry=GEO)
    assert [f.start for f in got_geo] == list(starts)
    assert len(got_geo) == len(got_legacy)
    for a, b in zip(got_geo, got_legacy):
        assert a.start == b.start and _same_result(a.result, b.result)


def test_stream_receiver_ctor_geometry_equals_legacy_kwargs(corpus):
    # field-for-field: the ctor resolution maps Geometry fields onto
    # exactly the attributes the legacy arguments set — fingerprint
    # (= compile keys + checkpoint identity) included
    r_geo = framebatch.StreamReceiver(geometry=GEO, check_fcs=True)
    r_old = framebatch.StreamReceiver(**LEGACY_KW)
    assert framebatch._stream_geometry(r_geo) == \
        framebatch._stream_geometry(r_old)
    # explicit per-knob args still override the geometry object
    r_mix = framebatch.StreamReceiver(geometry=GEO, chunk_len=8192,
                                      check_fcs=True)
    assert r_mix.chunk_len == 8192 and r_mix.frame_len == FRAME_LEN


def test_fleet_geometry_equals_legacy_kwargs_bit_identical(corpus):
    """The S-stream fleet at the same shared geometry: Geometry-built
    fleet vs legacy-kwargs fleet, zero new programs, identical
    emissions lane for lane."""
    stream, _starts, _legacy = corpus
    streams = [stream, stream[: len(stream) // 2].copy()]
    got_old, _ = framebatch.receive_streams(streams, **LEGACY_KW)
    with dispatch.no_recompile(rx._jit_stream_chunk_multi,
                               rx._jit_stream_decode_multi):
        got_geo, _ = framebatch.receive_streams(
            streams, check_fcs=True, geometry=GEO)
    assert [[f.start for f in lane] for lane in got_geo] == \
        [[f.start for f in lane] for lane in got_old]
    for lane_g, lane_o in zip(got_geo, got_old):
        for a, b in zip(lane_g, lane_o):
            assert _same_result(a.result, b.result)


def test_checkpoint_fingerprint_round_trip(corpus):
    """A Geometry-built receiver's checkpoint restores into a
    legacy-kwargs receiver (and back), and a LEGACY blob missing a
    post-format geometry field (sco_track) still restores — the
    _LEGACY_GEOMETRY_DEFAULTS contract the Geometry refactor must not
    disturb."""
    stream, _starts, _legacy = corpus
    r = framebatch.StreamReceiver(geometry=GEO, check_fcs=True)
    out = r.push(stream[: CHUNK + 100])
    blob, drained = r.checkpoint()
    rest = framebatch.StreamReceiver(checkpoint=blob, **LEGACY_KW)
    a = rest.push(stream[CHUNK + 100:]) + rest.flush()
    r2 = framebatch.StreamReceiver(checkpoint=blob, geometry=GEO,
                                   check_fcs=True)
    b = r2.push(stream[CHUNK + 100:]) + r2.flush()
    assert [f.start for f in a] == [f.start for f in b]
    for x, y in zip(a, b):
        assert _same_result(x.result, y.result)

    # a pre-sco_track blob: rebuild the same state without the field
    st = resilience.restore_carry(blob)
    legacy_geo = dict(st.geometry)
    assert legacy_geo.pop("sco_track") is False
    old_blob = resilience.checkpoint_carry(
        st, seen=st.seen, geometry=legacy_geo, state=st.state)
    r3 = framebatch.StreamReceiver(checkpoint=old_blob, geometry=GEO,
                                   check_fcs=True)
    c = r3.push(stream[CHUNK + 100:]) + r3.flush()
    assert [f.start for f in c] == [f.start for f in a]

    # a MISMATCHED geometry still refuses, Geometry-built or not
    with pytest.raises(resilience.CarryCheckpointError):
        framebatch.StreamReceiver(
            checkpoint=blob, check_fcs=True,
            geometry=GEO.replace(chunk_len=8192))
    del out, drained


# ------------------------------------------------------- the autotuner


def _fake_cost(costs):
    """cost_fn keyed on chunk_len (the axis the fake search varies)."""
    def fn(geo):
        return dict(costs[geo.chunk_len])
    return fn


def _fake_measure(speeds, fingerprints=None):
    """measure_fn keyed on chunk_len; same fingerprint everywhere
    unless a divergent one is injected."""
    def fn(geo):
        fp = (fingerprints or {}).get(geo.chunk_len, "identical")
        return {"sps": float(speeds[geo.chunk_len]), "fps": 1.0,
                "p50_ms": 1.0, "p99_ms": 2.0, "fingerprint": fp}
    return fn


def _fake_search_space(base):
    cands = [("half", base.replace(chunk_len=base.chunk_len // 2)),
             ("double", base.replace(chunk_len=base.chunk_len * 2))]
    costs = {base.chunk_len: {"bytes_per_sample": 10.0,
                              "flops_per_sample": 10.0},
             base.chunk_len // 2: {"bytes_per_sample": 15.0,
                                   "flops_per_sample": 15.0},
             base.chunk_len * 2: {"bytes_per_sample": 8.0,
                                  "flops_per_sample": 8.0}}
    speeds = {base.chunk_len: 100.0, base.chunk_len // 2: 150.0,
              base.chunk_len * 2: 130.0}
    return cands, costs, speeds


def test_default_candidates_carry_fused_demap_axis():
    # ISSUE 20: the rate-switched fused front makes fused_demap a
    # measured axis on the mixed/stream path — the default search
    # space must offer the lever alone AND the joint chunk x fused
    # move (the fused kernel shifts the bytes/flops balance, so the
    # chunk length that wins unfused need not win fused)
    base = Geometry().resolve()
    assert not base.fused_demap
    cands = dict(autotune.default_candidates(base))
    assert cands["fused_demap"].fused_demap is True
    assert cands["fused_demap"].chunk_len == base.chunk_len
    joint = cands[f"chunk{base.chunk_len * 2}_fused"]
    assert joint.fused_demap is True
    assert joint.chunk_len == base.chunk_len * 2
    # an already-fused base does not re-offer the axis
    fused_base = base.replace(fused_demap=True)
    assert not any("fused" in label for label, _ in
                   autotune.default_candidates(fused_base))


def test_autotune_cost_prune_rejects_analytically_worse():
    base = Geometry().resolve()
    cands, costs, speeds = _fake_search_space(base)
    out = autotune.run(base=base, candidates=cands,
                       cost_fn=_fake_cost(costs),
                       measure_fn=_fake_measure(speeds),
                       record=False, device_kind="faketpu",
                       platform="cpu", log=lambda s: None)
    # "half" is analytically worse: pruned BEFORE measurement, so its
    # (faster!) fake measurement can never make it the winner
    assert [r["label"] for r in out["pruned"]] == ["half"]
    assert out["winner"] == "double"
    assert out["speedup"] == pytest.approx(1.3)
    assert out["sps_tuned"] == pytest.approx(130.0)
    assert out["baseline_sps"] == pytest.approx(100.0)


def test_autotune_identity_gate_rejects_divergent_emissions():
    base = Geometry().resolve()
    cands, costs, speeds = _fake_search_space(base)
    out = autotune.run(
        base=base, candidates=cands, cost_fn=_fake_cost(costs),
        measure_fn=_fake_measure(
            speeds, fingerprints={base.chunk_len * 2: "DIVERGED"}),
        record=False, device_kind="faketpu", platform="cpu",
        log=lambda s: None)
    # the only survivor diverged -> the default wins by default
    assert out["identity_rejected"] == ["double"]
    assert out["winner"] == "default"
    assert out["speedup"] == pytest.approx(1.0)


def test_autotune_deterministic_and_tuned_reloads(tmp_path):
    ledger = str(tmp_path / "traj.jsonl")
    base = Geometry().resolve()
    cands, costs, speeds = _fake_search_space(base)
    kw = dict(base=base, candidates=cands, cost_fn=_fake_cost(costs),
              measure_fn=_fake_measure(speeds), record=True,
              path=ledger, device_kind="faketpu", platform="cpu",
              log=lambda s: None)
    out1 = autotune.run(**kw)
    out2 = autotune.run(**kw)
    # injected fakes -> the whole search is a pure function
    for k in ("winner", "geometry", "sps_tuned", "baseline_sps",
              "speedup", "pruned", "identity_rejected"):
        assert out1[k] == out2[k]
    # the record landed, keyed by device_kind, and tuned() reloads it
    recs = [json.loads(ln) for ln in open(ledger)]
    assert [r["stage"] for r in recs] == ["autotune", "autotune"]
    assert all(r["device_kind"] == "faketpu" and
               r["metric"] == "sps_tuned" for r in recs)
    g = Geometry.tuned("faketpu", path=ledger)
    assert g == Geometry.from_dict(out1["geometry"])
    assert g.chunk_len == base.chunk_len * 2
    # a different device kind falls back to the default, always
    assert Geometry.tuned("cpu", path=ledger) == Geometry()
    assert Geometry.tuned("faketpu",
                          path=str(tmp_path / "absent")) == Geometry()


def test_autotune_ledger_honors_bench_trajectory_env(tmp_path,
                                                     monkeypatch):
    ledger = str(tmp_path / "override.jsonl")
    monkeypatch.setenv("BENCH_TRAJECTORY", ledger)
    base = Geometry().resolve()
    cands, costs, speeds = _fake_search_space(base)
    out = autotune.run(base=base, candidates=cands,
                       cost_fn=_fake_cost(costs),
                       measure_fn=_fake_measure(speeds), record=True,
                       device_kind="faketpu", platform="cpu",
                       log=lambda s: None)
    assert out["recorded_to"] == ledger and os.path.exists(ledger)
    # tuned() reads the same override path by default
    assert Geometry.tuned("faketpu") == \
        Geometry.from_dict(out["geometry"])
