"""Native C buffer runtime vs the pure-python path (buf.c — the
reference's buf_*.c/bit.c equivalents, SURVEY.md §2.2)."""

import numpy as np
import pytest

from ziria_tpu.runtime import native_lib
from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                       write_stream, _format_dbg,
                                       _parse_dbg, _parse_bin, _format_bin)

pytestmark = pytest.mark.skipif(native_lib.load() is None,
                                reason="no native toolchain")


def test_bit_pack_unpack_roundtrip():
    rng = np.random.default_rng(0)
    for n in (1, 7, 8, 9, 1023, 4096):
        bits = rng.integers(0, 2, n).astype(np.uint8)
        packed = native_lib.pack_bits_native(bits)
        assert packed == np.packbits(bits, bitorder="little").tobytes()
        back = native_lib.unpack_bits_native(packed)
        np.testing.assert_array_equal(back[:n], bits)
        assert not back[n:].any()


def test_dbg_bits_native_matches_python():
    text = "0110 , 1\n101x01"
    got = native_lib.parse_dbg_bits_native(text)
    want = np.array([int(c) for c in text if c in "01"], np.uint8)
    np.testing.assert_array_equal(got, want)
    assert native_lib.format_dbg_bits_native(want) == "".join(
        map(str, want))


def test_dbg_ints_native_matches_python():
    vals = np.array([0, -1, 2147483647, -2147483648, 42, 0x1F], np.int64)
    text = ",".join(str(v) for v in vals[:-1]) + ",0x1F"
    got = native_lib.parse_dbg_ints_native(text)
    np.testing.assert_array_equal(got, vals)
    assert native_lib.format_dbg_ints_native(vals) == \
        ",".join(str(int(v)) for v in vals)


def test_dbg_ints_malformed():
    with pytest.raises(ValueError, match="malformed"):
        native_lib.parse_dbg_ints_native("12,ab")


@pytest.mark.parametrize("ty", ["bit", "int8", "int16", "int32",
                                "complex16", "complex32"])
@pytest.mark.parametrize("mode", ["dbg", "bin"])
def test_stream_roundtrip_all_types(tmp_path, ty, mode):
    rng = np.random.default_rng(3)
    if ty == "bit":
        arr = rng.integers(0, 2, 64).astype(np.uint8)
    elif ty in ("complex16", "complex32"):
        dt = np.int16 if ty == "complex16" else np.int32
        arr = rng.integers(-1000, 1000, (32, 2)).astype(dt)
    else:
        info = np.iinfo(np.dtype(ty))
        arr = rng.integers(info.min, info.max, 64).astype(ty)
    p = tmp_path / f"s.{mode}"
    write_stream(StreamSpec(ty=ty, path=str(p), mode=mode), arr)
    back = read_stream(StreamSpec(ty=ty, path=str(p), mode=mode))
    if ty == "bit" and mode == "bin":
        back = back[:arr.size]
    np.testing.assert_array_equal(back, arr)


def test_parse_paths_agree_with_fallback(monkeypatch):
    """The native and numpy paths must be bit-identical."""
    rng = np.random.default_rng(5)
    vals = rng.integers(-30000, 30000, 500).astype(np.int16)
    text = _format_dbg(vals, "int16")
    native = _parse_dbg(text, "int16")

    monkeypatch.setattr(native_lib, "parse_dbg_ints_native",
                        lambda *_: None)
    monkeypatch.setattr(native_lib, "parse_dbg_bits_native",
                        lambda *_: None)
    fallback = _parse_dbg(text, "int16")
    np.testing.assert_array_equal(native, fallback)


def test_dbg_int_overflow_rejected():
    """A literal beyond int64 must be reported as malformed, not wrap
    via signed-overflow UB (ADVICE r1)."""
    with pytest.raises(ValueError, match="malformed"):
        native_lib.parse_dbg_ints_native("99999999999999999999999")
    with pytest.raises(ValueError, match="malformed"):
        native_lib.parse_dbg_ints_native("0xFFFFFFFFFFFFFFFFFF")
    # INT64_MAX and INT64_MIN themselves still parse
    got = native_lib.parse_dbg_ints_native(
        "9223372036854775807,-9223372036854775808")
    assert got[0] == 9223372036854775807
    assert got[1] == -9223372036854775808
