"""The multi-rate transmitter as a program OF the framework
(examples/wifi_tx_rates.zir): frames arrive in-band as
[rate, len, bits...] on an int32 stream and leave as quantized IQ —
ONE generic body covering all eight 802.11a rates with runtime
parameters, the dual of wifi_rx.zir's decode_data (SURVEY.md §2.3,
§3.5). Ground truth is the library transmitter bit-for-bit at
quantization scale 512, and the flagship check closes the loop: the
in-language TX drives the in-language RX at every modulation."""

import os

import numpy as np
import pytest

from ziria_tpu.frontend import compile_file
from ziria_tpu.interp.interp import run
from ziria_tpu.ops.crc import append_crc32
from ziria_tpu.phy.wifi import tx
from ziria_tpu.utils.bits import bytes_to_bits

SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                   "wifi_tx_rates.zir")
RNG = np.random.default_rng(17)


def _frame_input(mbps, psdu):
    bits = np.asarray(bytes_to_bits(psdu)).astype(np.int32)
    return np.concatenate([[mbps, len(psdu)], bits]).astype(np.int32)


@pytest.mark.parametrize("mbps,n_bytes", [(6, 40), (9, 33), (12, 36),
                                          (18, 45), (24, 50), (36, 54),
                                          (48, 60), (54, 63)])
def test_tx_rates_matches_library(mbps, n_bytes):
    prog = compile_file(SRC)
    psdu = RNG.integers(0, 256, n_bytes).astype(np.uint8)
    out = np.asarray(run(prog.comp,
                         list(_frame_input(mbps, psdu))).out_array())
    want = np.round(np.asarray(tx.encode_frame(psdu, mbps)) * 512.0)
    assert out.shape == want.shape
    assert np.abs(out - want).max() <= 1.0


@pytest.mark.parametrize("mbps,n_bytes", [(6, 30), (18, 45), (36, 52),
                                          (54, 60)])
def test_in_language_tx_rx_loop(mbps, n_bytes):
    """The whole PHY as programs of the framework: multi-rate TX ->
    quantized wire -> receiver (which validates and strips the FCS) —
    payload bits round-trip exactly."""
    from ziria_tpu.backend import hybrid as H

    rng = np.random.default_rng(100 + mbps)
    txp = compile_file(SRC)
    rxp = H.hybridize(compile_file(os.path.join(
        os.path.dirname(SRC), "wifi_rx.zir")).comp)

    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    bits = np.asarray(append_crc32(bytes_to_bits(psdu))).astype(np.int32)
    xs = np.concatenate([[mbps, n_bytes + 4], bits]).astype(np.int32)
    iq = np.asarray(run(txp.comp, list(xs)).out_array())
    cap = np.clip(np.round(np.concatenate([
        rng.normal(scale=8.0, size=(60, 2)), iq,
        rng.normal(scale=8.0, size=(40, 2))])),
        -32768, 32767).astype(np.int16)
    out = np.asarray(run(rxp, [p for p in cap]).out_array(), np.uint8)
    np.testing.assert_array_equal(out, np.asarray(bytes_to_bits(psdu)))


def test_bad_header_consumed_stream_stays_synced():
    # an unknown rate (or oversize len) eats its frame and emits
    # nothing; the NEXT frame on the stream still transmits
    prog = compile_file(SRC)
    psdu = RNG.integers(0, 256, 36).astype(np.uint8)
    bad = _frame_input(11, RNG.integers(0, 256, 20).astype(np.uint8))
    good = _frame_input(12, psdu)
    out = np.asarray(run(prog.comp,
                         list(np.concatenate([bad, good]))).out_array())
    want = np.round(np.asarray(tx.encode_frame(psdu, 12)) * 512.0)
    assert out.shape == want.shape
    assert np.abs(out - want).max() <= 1.0


def test_hybrid_matches_interp():
    from ziria_tpu.backend import hybrid as H
    prog = compile_file(SRC)
    psdu = RNG.integers(0, 256, 48).astype(np.uint8)
    xs = list(_frame_input(24, psdu))
    want = run(prog.comp, xs).out_array()
    got = run(H.hybridize(prog.comp), xs).out_array()
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


def test_max_size_frame_at_high_rates():
    # code review r4: nbits rounds UP to a whole symbol, peaking at
    # 2160 for ndbps 216/144 — the buffer sizing must cover it
    prog = compile_file(SRC)
    for mbps in (36, 54):
        psdu = RNG.integers(0, 256, 256).astype(np.uint8)
        out = np.asarray(run(prog.comp,
                             list(_frame_input(mbps, psdu))).out_array())
        want = np.round(np.asarray(tx.encode_frame(psdu, mbps)) * 512.0)
        assert out.shape == want.shape
        assert np.abs(out - want).max() <= 1.0


def test_oversize_len_drains_and_stays_synced():
    # code review r4: an oversize len must still drain its payload so
    # the NEXT frame parses — no emission for the bad one
    prog = compile_file(SRC)
    psdu = RNG.integers(0, 256, 36).astype(np.uint8)
    bad = _frame_input(6, RNG.integers(0, 256, 300).astype(np.uint8))
    good = _frame_input(12, psdu)
    out = np.asarray(run(prog.comp,
                         list(np.concatenate([bad, good]))).out_array())
    want = np.round(np.asarray(tx.encode_frame(psdu, 12)) * 512.0)
    assert out.shape == want.shape
    assert np.abs(out - want).max() <= 1.0


def test_tx_rates_under_framebatch():
    # N transmit frames batched: the TX's take/emit machines ride
    # shared vmapped steps; every stream bit-identical to its solo run
    from ziria_tpu.backend import hybrid as H
    from ziria_tpu.backend.framebatch import StepBatcher, run_many

    hyb = H.hybridize(compile_file(SRC).comp)
    rng = np.random.default_rng(7)
    frames = []
    for mbps in (6, 12, 24, 54, 24, 24):
        psdu = rng.integers(0, 256, int(rng.integers(20, 80))
                            ).astype(np.uint8)
        frames.append(_frame_input(mbps, psdu))
    want = [run(hyb, list(f)) for f in frames]
    got = run_many(hyb, frames, batcher=StepBatcher(len(frames)))
    for w, g in zip(want, got):
        np.testing.assert_array_equal(np.asarray(w.out_array()),
                                      np.asarray(g.out_array()))


@pytest.mark.parametrize("seed", range(4))
def test_tx_rates_fuzz_vs_library(seed):
    rng = np.random.default_rng(400 + seed)
    prog = compile_file(SRC)
    mbps = int(rng.choice([6, 9, 12, 18, 24, 36, 48, 54]))
    nb = int(rng.integers(1, 257))
    psdu = rng.integers(0, 256, nb).astype(np.uint8)
    out = np.asarray(run(prog.comp,
                         list(_frame_input(mbps, psdu))).out_array())
    want = np.round(np.asarray(tx.encode_frame(psdu, mbps)) * 512.0)
    assert out.shape == want.shape, (mbps, nb)
    assert np.abs(out - want).max() <= 1.0, (mbps, nb)
