"""Typed stream buffers (dbg/bin modes, bit packing) and the CLI driver."""

import numpy as np
import pytest

from ziria_tpu.runtime.buffers import (StreamSpec, item_shape, read_stream,
                                       write_stream)
from ziria_tpu.runtime.cli import PROGS, main


# ----------------------------------------------------------------- buffers


@pytest.mark.parametrize("ty,data", [
    # bin-mode bit streams are byte-padded (no length header), so the
    # roundtrip fixture uses a multiple of 8 bits
    ("bit", np.array([1, 0, 1, 1, 0, 0, 1, 0, 1, 1, 0, 1, 1, 1, 0, 0],
                     np.uint8)),
    ("int8", np.array([-128, -1, 0, 1, 127], np.int8)),
    ("int16", np.array([-32768, -7, 0, 7, 32767], np.int16)),
    ("int32", np.array([-2**31, -1, 0, 1, 2**31 - 1], np.int32)),
    ("float32", np.array([-1.5, 0.0, 2.25, 1e10], np.float32)),
    ("float64", np.array([-1.5, 0.0, 2.25, 1e-300], np.float64)),
    ("complex16", np.array([[1, -2], [3, 4], [-5, 6]], np.int16)),
    ("complex32", np.array([[100000, -2], [3, 400000]], np.int32)),
])
@pytest.mark.parametrize("mode", ["dbg", "bin"])
def test_file_roundtrip(tmp_path, ty, data, mode):
    path = str(tmp_path / f"s.{mode}")
    spec = StreamSpec(kind="file", ty=ty, path=path, mode=mode)
    write_stream(spec, data)
    back = read_stream(spec)
    assert back.shape == (data.shape[0],) + item_shape(ty)
    np.testing.assert_array_equal(back, data)


def test_bit_bin_packing_order(tmp_path):
    # 8 bits -> exactly one byte, LSB-first like the reference's bit.c
    path = str(tmp_path / "b.bin")
    spec = StreamSpec(kind="file", ty="bit", path=path, mode="bin")
    write_stream(spec, np.array([1, 0, 0, 0, 0, 0, 0, 1], np.uint8))
    with open(path, "rb") as fh:
        raw = fh.read()
    assert raw == bytes([0b10000001])


def test_dummy_and_memory():
    d = read_stream(StreamSpec(kind="dummy", ty="complex16",
                               dummy_items=5))
    assert d.shape == (5, 2) and not d.any()
    m = write_stream(StreamSpec(kind="memory", ty="int32"),
                     np.arange(4))
    np.testing.assert_array_equal(m, np.arange(4))


def test_bad_specs_rejected():
    with pytest.raises(ValueError):
        StreamSpec(kind="file", ty="int32", path=None)
    with pytest.raises(ValueError):
        StreamSpec(kind="file", ty="nope", path="x")
    with pytest.raises(ValueError):
        StreamSpec(kind="file", ty="int32", path="x", mode="hex")


# --------------------------------------------------------------------- CLI


def test_cli_fir_matches_oracle(tmp_path):
    xs = np.linspace(-1, 1, 64).astype(np.float32)
    inp, out = str(tmp_path / "in.dbg"), str(tmp_path / "out.dbg")
    write_stream(StreamSpec(kind="file", ty="float32", path=inp), xs)
    rc = main([
        "--prog=fir", "--backend=jit",
        "--input=file", f"--input-file-name={inp}", "--input-type=float32",
        "--output=file", f"--output-file-name={out}",
        "--output-type=float32",
    ])
    assert rc == 0
    got = read_stream(StreamSpec(kind="file", ty="float32", path=out))

    from ziria_tpu.interp.interp import run
    want = np.asarray(run(PROGS["fir"](), list(xs)).out_array())
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_cli_fft_roundtrip_bin(tmp_path):
    rng = np.random.default_rng(0)
    xs = rng.integers(-100, 100, (128, 2)).astype(np.int16)
    inp = str(tmp_path / "in.bin")
    mid = str(tmp_path / "mid.bin")
    out = str(tmp_path / "out.bin")
    write_stream(StreamSpec(kind="file", ty="complex16", path=inp,
                            mode="bin"), xs)
    common = ["--input-type=complex16", "--output-type=complex16",
              "--input-file-mode=bin", "--output-file-mode=bin"]
    assert main(["--prog=fft64", f"--input-file-name={inp}",
                 f"--output-file-name={mid}"] + common) == 0
    assert main(["--prog=ifft64", f"--input-file-name={mid}",
                 f"--output-file-name={out}"] + common) == 0
    got = read_stream(StreamSpec(kind="file", ty="complex16", path=out,
                                 mode="bin"))
    # fft->ifft roundtrip recovers the input (pairs are float through the
    # pipeline, written back as rounded complex16 text/bin)
    np.testing.assert_allclose(got, xs, atol=1.0)


def test_cli_scramble_bits_dbg(tmp_path):
    rng = np.random.default_rng(1)
    bits = rng.integers(0, 2, 96).astype(np.uint8)
    inp, out = str(tmp_path / "b.dbg"), str(tmp_path / "s.dbg")
    write_stream(StreamSpec(kind="file", ty="bit", path=inp), bits)
    rc = main([
        "--prog=scramble", "--backend=interp",
        f"--input-file-name={inp}", "--input-type=bit",
        f"--output-file-name={out}", "--output-type=bit",
    ])
    assert rc == 0
    got = read_stream(StreamSpec(kind="file", ty="bit", path=out))

    from ziria_tpu.ops.scramble import np_lfsr_sequence_127
    from ziria_tpu.phy.wifi.tx import DEFAULT_SCRAMBLER_SEED, _seed_bits_np
    seq = np.resize(
        np_lfsr_sequence_127(_seed_bits_np(DEFAULT_SCRAMBLER_SEED)),
        bits.size)
    np.testing.assert_array_equal(got, bits ^ seq)


def test_cli_list_progs(capsys):
    assert main(["--list-progs"]) == 0
    listed = capsys.readouterr().out.split()
    assert "fir" in listed and "wifi_tx_sym_54" in listed


def test_cli_unknown_prog():
    with pytest.raises(SystemExit):
        main(["--prog=nope"])


def test_package_import_stays_jax_free():
    # `import ziria_tpu` must not drag in jax/XLA init (multi-second);
    # heavy deps load lazily when a backend/pass actually runs
    import subprocess
    import sys
    # this interpreter's sitecustomize preloads jax, so the check is
    # "importing ziria_tpu adds no jax", not "jax is absent"
    code = ("import sys; pre = 'jax' in sys.modules; import ziria_tpu; "
            "sys.exit(1 if ('jax' in sys.modules and not pre) else 0)")
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", code], cwd=repo)
    assert r.returncode == 0
