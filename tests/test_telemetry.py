"""The runtime telemetry layer (utils/telemetry) and its dispatch
emitters (ISSUE 7): span nesting and thread-safety, histogram
quantile bounds vs exact sorted percentiles, Chrome trace-event JSON
schema validity, the trace_report summarizer, the Prometheus-style
exposition, the dispatch/gauge/compile emitter wiring, and — because
the hot paths carry their instrumentation permanently — a pinned
near-zero-overhead check for the disabled path."""

import importlib.util
import json
import math
import os
import threading
import time

import numpy as np
import pytest

from ziria_tpu.utils import dispatch, telemetry

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(TOOLS, "trace_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------------ spans


def test_span_nesting_contained_and_labelled():
    with telemetry.tracing() as tr:
        with telemetry.span("outer"):
            time.sleep(0.002)
            with telemetry.span("inner"):
                time.sleep(0.001)
    evs = {e["name"]: e for e in tr.events()}
    assert set(evs) == {"outer", "inner"}
    outer, inner = evs["outer"], evs["inner"]
    assert outer["ph"] == inner["ph"] == "X"
    # same thread, and the inner span's [ts, ts+dur) lies inside the
    # outer's — the containment Chrome's nesting model is built on
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
    assert inner["dur"] >= 1000 and outer["dur"] >= inner["dur"]


def test_spans_threadsafe_none_lost():
    """Concurrent spans from many threads: no lost events, and each
    worker's spans all carry that worker's tid (thread idents may be
    REUSED across workers whose lifetimes don't overlap — that is OS
    behavior, not a trace defect — so cross-worker distinctness is
    deliberately not asserted; a gate barrier keeps them overlapping
    enough to exercise real contention)."""
    n_threads, n_spans = 8, 50
    gate = threading.Barrier(n_threads)
    with telemetry.tracing() as tr:
        def worker(i):
            gate.wait()
            for _k in range(n_spans):
                with telemetry.span(f"t{i}"):
                    pass
        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    evs = tr.events()
    assert len(evs) == n_threads * n_spans
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e["tid"])
    for i in range(n_threads):
        assert len(by_name[f"t{i}"]) == n_spans
        assert len(set(by_name[f"t{i}"])) == 1


def test_nested_same_object_activation_stays_balanced():
    """Activating the SAME Trace/MetricsRegistry object in nested
    blocks must deactivate one level per exit, not all of them — the
    outer block keeps collecting after the inner one closes."""
    r = telemetry.MetricsRegistry()
    with telemetry.collect(r):
        with telemetry.collect(r):
            pass
        telemetry.count("after_inner")
    assert r.find("after_inner").value == 1
    assert not telemetry.active()
    t = telemetry.Trace()
    with telemetry.tracing(trace=t):
        with telemetry.tracing(trace=t):
            pass
        with telemetry.span("after"):
            pass
    assert not telemetry.active()
    assert [e["name"] for e in t.events() if e["ph"] == "X"] == ["after"]


def test_overlapping_traces_each_see_their_window():
    with telemetry.tracing() as a:
        with telemetry.span("one"):
            pass
        with telemetry.tracing() as b:
            with telemetry.span("two"):
                pass
        with telemetry.span("three"):
            pass
    assert [e["name"] for e in a.events()] == ["one", "two", "three"]
    assert [e["name"] for e in b.events()] == ["two"]


# ------------------------------------------------------------- histograms


def test_histogram_quantile_bounds_vs_exact_percentiles():
    rng = np.random.default_rng(7)
    # log-uniform over ~6 decades: every bucket family gets exercised
    vals = np.exp(rng.uniform(np.log(1e-6), np.log(1.0), 5000))
    h = telemetry.Histogram()
    for v in vals:
        h.observe(float(v))
    s = np.sort(vals)
    for q in (0.5, 0.9, 0.99):
        exact = s[max(1, math.ceil(q * len(s))) - 1]   # nearest rank
        bound = h.quantile(q)
        # the contract: an upper bound never more than 2x above truth
        assert exact <= bound <= 2.0 * exact, (q, exact, bound)
    assert h.max == pytest.approx(float(s[-1]))
    assert h.min == pytest.approx(float(s[0]))
    assert h.sum == pytest.approx(float(vals.sum()), rel=1e-9)
    assert h.count == len(vals)


def test_histogram_exact_powers_and_edge_cases():
    h = telemetry.Histogram()
    assert h.quantile(0.5) is None               # empty
    for v in (0.25, 0.5, 1.0, 2.0):
        h.observe(v)
    # exact powers of two sit at their own bucket's UPPER edge: the
    # p-quantile bound of a single-value bucket is the value itself
    assert h.quantile(0.01) == 0.25
    assert h.quantile(1.0) == 2.0
    h2 = telemetry.Histogram()
    h2.observe(0.0)
    h2.observe(-1.0)
    assert h2.quantile(0.5) <= 0.0               # underflow bucket
    assert h2.count == 2


def test_histogram_summary_block():
    h = telemetry.Histogram()
    for v in (0.001, 0.002, 0.004):
        h.observe(v)
    s = h.summary(scale=1e3, ndigits=4)
    assert s["count"] == 3
    assert s["mean"] == pytest.approx(7.0 / 3, rel=1e-3)
    assert s["max"] == pytest.approx(4.0)
    assert s["p50"] >= 2.0 and s["p99"] >= 4.0
    assert telemetry.Histogram().summary() == {"count": 0}


# ------------------------------------------------------ registry/metrics


def test_registry_counters_gauges_and_snapshot():
    r = telemetry.MetricsRegistry()
    r.counter("frames", kind="data").inc(3)
    r.counter("frames", kind="data").inc(2)     # get-or-create: same
    g = r.gauge("depth")
    g.set(1.0, t=10.0)
    g.set(3.0, t=11.0)
    g.set(2.0, t=12.0)
    snap = r.snapshot()
    assert snap['frames{kind="data"}'] == 5
    assert snap["depth"]["last"] == 2.0
    assert snap["depth"]["max"] == 3.0          # series, not just max
    assert [v for _t, v in snap["depth"]["samples"]] == [1.0, 3.0, 2.0]
    json.dumps(snap)                             # JSON-serializable
    with pytest.raises(TypeError):
        r.gauge("frames", kind="data")           # type collision


def test_registry_prometheus_exposition():
    r = telemetry.MetricsRegistry()
    r.counter("ziria_dispatches_total", site="rx.sync").inc(4)
    r.gauge("ziria_gauge", site="rx.stream_inflight").set(2.0)
    h = r.histogram("ziria_dispatch_seconds", site="rx.sync")
    h.observe(0.001)
    h.observe(0.003)
    text = r.exposition()
    assert "# TYPE ziria_dispatches_total counter" in text
    assert 'ziria_dispatches_total{site="rx.sync"} 4' in text
    assert "# TYPE ziria_gauge gauge" in text
    assert 'ziria_gauge{site="rx.stream_inflight"} 2.0' in text
    assert "# TYPE ziria_dispatch_seconds histogram" in text
    assert 'le="+Inf"' in text
    assert 'ziria_dispatch_seconds_count{site="rx.sync"} 2' in text
    # cumulative bucket discipline: counts never decrease with le
    cums = [int(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
            if ln.startswith("ziria_dispatch_seconds_bucket")]
    assert cums == sorted(cums) and cums[-1] == 2


# ------------------------------------------------------ trace JSON schema


def test_chrome_trace_json_schema(tmp_path):
    path = tmp_path / "trace.json"
    with telemetry.tracing(str(path)) as tr:
        with telemetry.span("a", args={"k": 1}):
            pass
        tr.counter("lvl", 2.0)
        telemetry.record_compile("cache_growth:test", n=3,
                                 args={"new_entries": 3})
        telemetry.record_compile("xla:fake_compile", seconds=0.01)
    obj = json.loads(path.read_text())
    assert isinstance(obj["traceEvents"], list)
    assert obj["displayTimeUnit"] == "ms"
    by_ph = {}
    for e in obj["traceEvents"]:
        assert isinstance(e["name"], str)
        assert "ts" in e and "pid" in e and "ph" in e
        by_ph.setdefault(e["ph"], []).append(e)
    assert all("dur" in e and "tid" in e for e in by_ph["X"])
    # the compile span sits in the compile category with its duration
    comp = [e for e in by_ph["X"] if e["cat"] == "compile"]
    assert comp and comp[0]["name"] == "xla:fake_compile" \
        and comp[0]["dur"] == pytest.approx(1e4, rel=1e-3)
    # the cache-growth delta is an instant marker carrying the delta
    inst = by_ph["i"][0]
    assert inst["name"] == "cache_growth:test" \
        and inst["args"]["new_entries"] == 3
    # counter samples carry {"value": v}
    assert by_ph["C"][0]["args"]["value"] == 2.0


def test_trace_report_summarizes_real_trace(tmp_path):
    path = tmp_path / "trace.json"
    with telemetry.tracing(str(path)) as tr:
        for _ in range(4):
            with telemetry.span("rx.stream_chunk"):
                time.sleep(0.001)
        with telemetry.span("rx.stream_decode"):
            pass
        tr.counter("rx.stream_inflight", 2.0)
        telemetry.record_compile("xla:fake", seconds=0.5)
        telemetry.record_compile("cache_growth:_jit_x", n=2)
    tr_mod = _load_trace_report()
    summary, table = tr_mod.summarize_file(str(path))
    spans = summary["spans"]
    assert spans["rx.stream_chunk"]["count"] == 4
    assert spans["rx.stream_chunk"]["p50_ms"] >= 1.0
    assert spans["rx.stream_chunk"]["p99_ms"] >= \
        spans["rx.stream_chunk"]["p50_ms"]
    assert spans["rx.stream_chunk"]["total_ms"] >= 4.0
    assert summary["compiles"]["xla:fake"]["total_ms"] == \
        pytest.approx(500.0, rel=1e-3)
    assert summary["compile_markers"] == {"cache_growth:_jit_x": 2}
    assert summary["counters"]["rx.stream_inflight"]["max"] == 2.0
    for needle in ("rx.stream_chunk", "xla:fake", "p99 ms",
                   "rx.stream_inflight"):
        assert needle in table
    # and the CLI entry point parses the same file
    assert tr_mod.main([str(path)]) == 0


# ------------------------------------------------------ dispatch emitters


def test_dispatch_sites_emit_spans_histograms_counters():
    with telemetry.tracing() as tr, telemetry.collect() as reg:
        with dispatch.count_dispatches() as d:
            for _ in range(5):
                with dispatch.timed("rx.fake_site"):
                    pass
            dispatch.record("rx.bare", 2)
    # DispatchCount API unchanged
    assert d.counts["rx.fake_site"] == 5 and d.counts["rx.bare"] == 2
    # trace got one span per timed() block
    assert [e["name"] for e in tr.events()].count("rx.fake_site") == 5
    # registry got the counter and the latency histogram
    assert reg.find(telemetry.DISPATCH_COUNTER,
                    site="rx.fake_site").value == 5
    assert reg.find(telemetry.DISPATCH_COUNTER, site="rx.bare").value \
        == 2
    h = reg.find(telemetry.DISPATCH_HISTOGRAM, site="rx.fake_site")
    assert h.count == 5 and h.quantile(0.99) is not None
    # bare record() carries no duration: counter only
    assert reg.find(telemetry.DISPATCH_HISTOGRAM, site="rx.bare") is None


def test_record_gauge_emits_timeseries_and_counter_track():
    with telemetry.tracing() as tr, telemetry.collect() as reg:
        with dispatch.count_dispatches() as d:
            for v in (1, 2, 1):
                dispatch.record_gauge("rx.fake_inflight", v)
    assert d.gauges["rx.fake_inflight"] == 2        # max, as before
    g = reg.find(telemetry.GAUGE_METRIC, site="rx.fake_inflight")
    assert [v for _t, v in g.samples] == [1.0, 2.0, 1.0]  # the series
    cs = [e for e in tr.events() if e["ph"] == "C"]
    assert [e["args"]["value"] for e in cs] == [1.0, 2.0, 1.0]


def test_telemetry_without_dispatch_counter_active():
    # a trace alone (no count_dispatches) still sees the sites — the
    # CLI --trace path runs exactly this shape
    with telemetry.tracing() as tr:
        with dispatch.timed("rx.solo"):
            pass
    assert [e["name"] for e in tr.events()] == ["rx.solo"]


def test_cache_growth_reports_compile_delta():
    import functools

    @functools.lru_cache(maxsize=None)
    def _jit_fake(n):
        return object()

    with telemetry.tracing() as tr:
        with dispatch.cache_growth(_jit_fake) as g:
            _jit_fake(1)
            _jit_fake(2)
    assert g.total == 2
    evs = [e for e in tr.events()
           if e["name"] == "cache_growth:_jit_fake"]
    assert len(evs) == 1 and evs[0]["args"]["new_entries"] == 2
    # no delta -> no event
    with telemetry.tracing() as tr2:
        with dispatch.cache_growth(_jit_fake):
            _jit_fake(1)
    assert tr2.events() == []


def test_dispatchcount_concurrent_per_instance_locks():
    n_threads, n_each = 8, 300
    with dispatch.count_dispatches() as outer:
        with dispatch.count_dispatches() as inner:
            def worker(i):
                for _ in range(n_each):
                    dispatch.record(f"site{i % 2}",
                                    seconds=1e-6)
                    dispatch.record_gauge("lvl", i)
            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(n_threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    # no lost updates under per-instance locking, and BOTH active
    # counters (nested) saw every event
    for d in (outer, inner):
        assert d.total == n_threads * n_each
        assert d.counts["site0"] == d.counts["site1"] \
            == n_threads * n_each // 2
        assert d.gauges["lvl"] == n_threads - 1
        assert d.total_time == pytest.approx(
            n_threads * n_each * 1e-6, rel=0.5)


# ------------------------------------------------------- disabled path


def test_disabled_path_overhead_pinned():
    """The hot paths carry record()/timed()/record_gauge()/span()
    permanently; with nothing active each call must stay in the
    no-allocation fast path. Pinned as a generous wall bound (CI boxes
    are noisy): 50k disabled calls in well under a second — a
    regression to lock-taking or event building blows this by orders
    of magnitude."""
    assert not telemetry.active() and not dispatch._ACTIVE
    n = 50_000
    t0 = time.perf_counter()
    for _ in range(n):
        dispatch.record("x")
    t_record = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        dispatch.record_gauge("x", 1.0)
    t_gauge = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        with dispatch.timed("x"):
            pass
    t_timed = time.perf_counter() - t0
    # ~0.1-0.3 µs/call measured; the pin is 20x that
    assert t_record / n < 5e-6, f"record() disabled: {t_record/n:.2e}s"
    assert t_gauge / n < 5e-6, f"record_gauge() disabled: {t_gauge/n:.2e}s"
    assert t_timed / n < 2e-5, f"timed() disabled: {t_timed/n:.2e}s"


# ------------------------------------------------------------- CLI knob


def test_cli_trace_and_metrics_dump(tmp_path, capsys):
    """--trace exports a parseable Chrome trace via the scoped
    ZIRIA_TRACE env (cleared after the invocation); --metrics-dump
    prints the Prometheus exposition."""
    from ziria_tpu.runtime.buffers import StreamSpec, write_stream
    from ziria_tpu.runtime.cli import main as cli_main

    inf, outf = tmp_path / "in.dbg", tmp_path / "out.dbg"
    tracef = tmp_path / "trace.json"
    rng = np.random.default_rng(0)
    write_stream(StreamSpec(ty="bit", path=str(inf), mode="dbg"),
                 rng.integers(0, 2, 64).astype(np.uint8))
    rc = cli_main([
        "--prog=scramble",
        "--input=file", f"--input-file-name={inf}",
        "--input-file-mode=dbg", "--input-type=bit",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=dbg", "--output-type=bit",
        "--backend=jit", f"--trace={tracef}", "--metrics-dump",
    ])
    assert rc == 0
    assert os.environ.get("ZIRIA_TRACE") is None     # scoped, restored
    obj = json.loads(tracef.read_text())
    assert isinstance(obj["traceEvents"], list)
    _summary, table = _load_trace_report().summarize_file(str(tracef))
    err = capsys.readouterr().err
    assert "telemetry trace written to" in err
    # the exposition dump ran (its marker line always prints; the
    # metric families below it depend on what the warm caches skipped)
    assert "metrics exposition" in err
