"""802.11a TX chain vs independent numpy oracle, plus the DSL pipeline
form vs the frame-level form."""

import numpy as np
import pytest

from ziria_tpu.backend.execute import run_jit
from ziria_tpu.ops import coding, cplx, interleave, modulate, ofdm, scramble
from ziria_tpu.phy.wifi import tx
from ziria_tpu.phy.wifi.params import RATES, n_symbols
from ziria_tpu.utils.bits import uint_to_bits
from ziria_tpu.utils.diff import assert_stream_eq
from tests.oracles.wifi_tx_ref import tx_frame_ref

RNG = np.random.default_rng(7)


@pytest.mark.parametrize("rate", [6, 9, 12, 18, 24, 36, 48, 54])
def test_tx_frame_vs_oracle(rate):
    psdu = RNG.integers(0, 2, 8 * 25).astype(np.uint8)  # 25-byte PSDU
    got = cplx.to_complex(np.asarray(tx.encode_frame_bits(psdu, RATES[rate])))
    want = tx_frame_ref(psdu, rate)
    assert got.shape == want.shape
    assert_stream_eq(got, want, atol=2e-4, name=f"tx@{rate}")


def test_tx_frame_length():
    rate = RATES[24]
    psdu = np.zeros(8 * 100, np.uint8)
    out = np.asarray(tx.encode_frame_bits(psdu, rate))
    n_sym = n_symbols(100, rate)
    assert out.shape == (320 + 80 + 80 * n_sym, 2)


def test_signal_field_parity_and_layout():
    bits = np.asarray(tx.signal_field_bits(RATES[36], 100))
    assert bits.shape == (24,)
    # tail bits zero, parity makes first 18 bits even
    assert bits[18:].sum() == 0
    assert bits[:18].sum() % 2 == 0
    # RATE bits R1..R4 = 1011 for 36 Mbps
    assert list(bits[:4]) == [1, 0, 1, 1]
    # LENGTH=100 LSB-first in bits 5..16
    assert int(sum(int(b) << k for k, b in enumerate(bits[5:17]))) == 100


def test_batched_frames_vmap():
    import jax
    rate = RATES[12]
    psdus = RNG.integers(0, 2, (4, 8 * 30)).astype(np.uint8)
    batched = jax.jit(jax.vmap(lambda p: tx.encode_frame_bits(p, rate)))
    got = np.asarray(batched(psdus))
    for i in range(4):
        want = np.asarray(tx.encode_frame_bits(psdus[i], rate))
        assert_stream_eq(got[i], want, atol=1e-5, name=f"frame{i}")


@pytest.mark.parametrize("rate", [6, 54])
def test_tx_symbol_pipeline_matches_ops(rate):
    """The DSL pipeline form (map_accum stages) produces the same DATA
    symbols as applying the ops to the whole stream at once."""
    p = RATES[rate]
    n_sym = 5
    bits = RNG.integers(0, 2, n_sym * p.n_dbps).astype(np.uint8)

    got = run_jit(tx.tx_symbol_pipeline(rate), bits, width=2)

    seed = uint_to_bits(np.uint32(0b1011101), 7)
    scrambled = scramble.scramble_bits(bits, seed)
    coded = coding.puncture(coding.conv_encode(scrambled), p.coding)
    inter = interleave.interleave(coded, p.n_cbps, p.n_bpsc)
    syms = modulate.modulate(inter, p.n_bpsc).reshape(n_sym, 48, 2)
    bins = ofdm.map_subcarriers(syms, symbol_index0=1)
    want = np.asarray(ofdm.ofdm_modulate(bins)).reshape(-1, 2)

    assert_stream_eq(np.asarray(got), want, atol=2e-5, name=f"pipe@{rate}")


def test_add_fcs_changes_length():
    psdu = np.zeros(10, np.uint8)
    a = np.asarray(tx.encode_frame(psdu, 6))
    b = np.asarray(tx.encode_frame(psdu, 6, add_fcs=True))
    assert b.shape[0] > a.shape[0]
