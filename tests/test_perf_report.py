"""Perf ledger (BENCH_TRAJECTORY.jsonl + tools/perf_report.py) and the
trace-compare gate (tools/trace_report.py --compare): the ISSUE 9
regression machinery.

The load-bearing pins:
- a >= 20% synthetic regression makes ``perf_report --check`` (and the
  ``--diff`` form) exit NONZERO — the gate tools/precommit.sh runs;
- movement within tolerance passes;
- direction-aware comparison (lint findings going UP is a regression
  even though the number is "lower is better");
- cpu smoke runs never gate tpu runs (platform-matched comparison);
- bench.py's ``_partial`` mirrors a stage's primary metric into the
  trajectory as ONE normalized flat record, honoring the
  BENCH_TRAJECTORY path override (so tests and smoke harnesses never
  dirty the committed ledger);
- the one-time backfill parses the metric JSON out of the historic
  BENCH_r*.json "tail" wrapper and refuses to run twice.

Pure-CPU, no jax: both tools are stdlib by design (they must work
while the TPU probe hangs), and so are these tests.
"""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, path):
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


pr = _load("perf_report", os.path.join(REPO, "tools", "perf_report.py"))
tr = _load("trace_report", os.path.join(REPO, "tools",
                                        "trace_report.py"))


def _rec(run, stage, value, metric="sps", platform="cpu", t=100.0,
         direction="higher", **kv):
    return {"run_id": run, "unix": t, "stage": stage, "metric": metric,
            "value": value, "platform": platform, "partial": False,
            "direction": direction, "source": "bench", **kv}


def _write(tmp_path, recs, name="traj.jsonl"):
    p = tmp_path / name
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(p)


# -------------------------------------------------------------- the gate


def test_check_fails_on_20pct_regression(tmp_path):
    path = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "streaming_rx", 790.0, t=200),   # -21%
    ])
    rc = pr.main(["--path", path, "--check"])
    assert rc == 1


def test_check_passes_within_tolerance(tmp_path):
    path = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "streaming_rx", 950.0, t=200),   # -5% < 10% tol
    ])
    assert pr.main(["--path", path, "--check"]) == 0


def test_check_tolerance_is_configurable(tmp_path):
    path = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "streaming_rx", 790.0, t=200),
    ])
    assert pr.main(["--path", path, "--check",
                    "--tolerance", "0.5"]) == 0
    assert pr.main(["--path", path, "--check", "--tolerance", "0.5",
                    "--stage-tolerance", "streaming_rx=0.1"]) == 1


def test_lower_is_better_direction(tmp_path):
    # lint findings going 0 -> 2 is a regression; 2 -> 0 is not
    path = _write(tmp_path, [
        _rec("r1", "lint", 0, metric="findings_total",
             direction="lower", t=100),
        _rec("r2", "lint", 2, metric="findings_total",
             direction="lower", t=200),
    ])
    assert pr.main(["--path", path, "--check"]) == 1
    path2 = _write(tmp_path, [
        _rec("r1", "lint", 2, metric="findings_total",
             direction="lower", t=100),
        _rec("r2", "lint", 0, metric="findings_total",
             direction="lower", t=200),
    ], name="t2.jsonl")
    assert pr.main(["--path", path2, "--check"]) == 0


def test_cpu_smoke_never_gates_tpu_runs(tmp_path):
    # the latest run is a cpu smoke 100x slower than the tpu capture:
    # comparing across platforms would scream regression; the gate
    # must match platforms instead
    path = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1e8, platform="tpu", t=100),
        _rec("r2", "streaming_rx", 1e6, platform="cpu", t=200),
    ])
    assert pr.main(["--path", path, "--check"]) == 0
    # and a second cpu run gates against the first cpu run
    path2 = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1e8, platform="tpu", t=100),
        _rec("r2", "streaming_rx", 1e6, platform="cpu", t=200),
        _rec("r3", "streaming_rx", 5e5, platform="cpu", t=300),
    ], name="t2.jsonl")
    assert pr.main(["--path", path2, "--check"]) == 1


def test_device_kind_mismatch_never_gates(tmp_path):
    # ISSUE 16: matching extended from platform-only to device_kind.
    # Two autotune records, both platform=cpu artifacts, but measured
    # on DIFFERENT device kinds (a v5e winner vs a cpu smoke): the
    # 100x gap must read as "not comparable", never as a regression
    path = _write(tmp_path, [
        _rec("r1", "autotune", 1e8, metric="sps_tuned", t=100,
             device_kind="TPU v5e"),
        _rec("r2", "autotune", 1e6, metric="sps_tuned", t=200,
             device_kind="cpu"),
    ])
    assert pr.main(["--path", path, "--check"]) == 0
    rows, regressions = pr.diff_runs(
        *[pr.group_runs(pr.load_trajectory(path))[r]
          for r in ("r1", "r2")])
    assert regressions == []
    assert any("device_kind mismatch" in row[-1] for row in rows)
    # same device kind gates as before
    path2 = _write(tmp_path, [
        _rec("r1", "autotune", 1e6, metric="sps_tuned", t=100,
             device_kind="cpu"),
        _rec("r2", "autotune", 5e5, metric="sps_tuned", t=200,
             device_kind="cpu"),
    ], name="t2.jsonl")
    assert pr.main(["--path", path2, "--check"]) == 1


def test_device_kind_absent_matches_absent(tmp_path):
    # legacy records (no device_kind field) keep gating each other —
    # the new key must not amnesty the whole historical ledger
    path = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "streaming_rx", 700.0, t=200),
    ])
    assert pr.main(["--path", path, "--check"]) == 1
    # but a legacy record never gates a device_kind-stamped one
    path2 = _write(tmp_path, [
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "streaming_rx", 700.0, t=200, device_kind="cpu"),
    ], name="t2.jsonl")
    assert pr.main(["--path", path2, "--check"]) == 0


def test_numpy_baseline_noise_never_gates(tmp_path):
    # the per-run baseline measurement swings with host load (r4 saw
    # 4.08-6.40 M sps for identical code) — it is ledger context, not
    # a gated metric; a real stage regressing in the same pair still
    # fails
    path = _write(tmp_path, [
        _rec("r1", "numpy_baseline", 6.4e6, t=100),
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "numpy_baseline", 4.0e6, t=200),   # -37%: host load
        _rec("r2", "streaming_rx", 1000.0, t=200),
    ])
    assert pr.main(["--path", path, "--check"]) == 0
    path2 = _write(tmp_path, [
        _rec("r1", "numpy_baseline", 6.4e6, t=100),
        _rec("r1", "streaming_rx", 1000.0, t=100),
        _rec("r2", "numpy_baseline", 4.0e6, t=200),
        _rec("r2", "streaming_rx", 500.0, t=200),
    ], name="t2.jsonl")
    assert pr.main(["--path", path2, "--check"]) == 1


def test_check_with_too_little_history_passes(tmp_path):
    assert pr.main(["--path", str(tmp_path / "none.jsonl"),
                    "--check"]) == 0
    path = _write(tmp_path, [_rec("r1", "streaming_rx", 1.0)])
    assert pr.main(["--path", path, "--check"]) == 0


def test_diff_exit_and_rows(tmp_path, capsys):
    path = _write(tmp_path, [
        _rec("r1", "fused_link", 100.0, metric="fps_fused", t=100),
        _rec("r1", "ber_sweep", 50.0, metric="points_per_s_sweep",
             t=100),
        _rec("r2", "fused_link", 60.0, metric="fps_fused", t=200),
    ])
    assert pr.main(["--path", path, "--diff", "r1", "r2"]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "only in one run" in out
    assert pr.main(["--path", path, "--diff", "r1", "nope"]) == 2


def test_garbage_lines_and_latest_record_wins(tmp_path):
    p = tmp_path / "traj.jsonl"
    with open(p, "w") as f:
        f.write("not json\n")
        f.write(json.dumps(_rec("r1", "s", 1.0, t=100)) + "\n")
        f.write(json.dumps(_rec("r1", "s", 2.0, t=150)) + "\n")
    runs = pr.group_runs(pr.load_trajectory(str(p)))
    assert runs["r1"]["metrics"][("s", "sps")]["value"] == 2.0


# ---------------------------------------------------------- bench append


def _bench():
    return _load("bench_for_traj", os.path.join(REPO, "bench.py"))


def test_partial_mirrors_primary_metric_to_trajectory(tmp_path,
                                                      monkeypatch):
    b = _bench()
    traj = tmp_path / "traj.jsonl"
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "part.jsonl"))
    monkeypatch.setenv("BENCH_TRAJECTORY", str(traj))
    b._partial("rX", "streaming_rx", sps_streaming=123.4,
               platform="cpu", frames=8)
    b._partial("rX", "backend_up", platform="cpu")     # no metric
    b._partial("rX", "streaming_rx", error="boom", platform="cpu")
    recs = pr.load_trajectory(str(traj))
    assert len(recs) == 1
    assert recs[0]["stage"] == "streaming_rx"
    assert recs[0]["metric"] == "sps_streaming"
    assert recs[0]["value"] == 123.4
    assert recs[0]["platform"] == "cpu"
    assert recs[0]["direction"] == "higher"


def test_traj_append_honors_env_override_and_never_raises(tmp_path,
                                                          monkeypatch):
    b = _bench()
    monkeypatch.setenv("BENCH_TRAJECTORY",
                       str(tmp_path / "sub" / "nope.jsonl"))
    # unwritable (missing dir): best-effort, must not raise
    b._traj_append("s", "m", 1.0, "r", "cpu")
    monkeypatch.setenv("BENCH_TRAJECTORY", str(tmp_path / "t.jsonl"))
    b._traj_append("s", "m", 1.0, "r", "cpu", resumed=True)
    recs = pr.load_trajectory(str(tmp_path / "t.jsonl"))
    assert len(recs) == 1 and recs[0]["resumed"] is True


def test_batch_sweep_records_keyed_per_width(tmp_path, monkeypatch):
    # sweep probes are per-width: run A finishing at B=1024 and run B
    # whose budget stopped at B=256 must land in DIFFERENT series, or
    # the gate fakes a 2-4x regression out of a width mismatch
    b = _bench()
    traj = tmp_path / "traj.jsonl"
    monkeypatch.setattr(b, "PARTIAL_PATH", str(tmp_path / "p.jsonl"))
    monkeypatch.setenv("BENCH_TRAJECTORY", str(traj))
    b._partial("rA", "batch_sweep", tpu_sps=4e8, batch=1024,
               platform="tpu")
    b._partial("rB", "batch_sweep", tpu_sps=1e8, batch=256,
               platform="tpu")
    recs = pr.load_trajectory(str(traj))
    assert {r["stage"] for r in recs} == {"batch_sweep:1024",
                                         "batch_sweep:256"}
    runs = pr.group_runs(recs)
    _rows, regressions = pr.diff_runs(runs["rA"], runs["rB"])
    assert regressions == []


def test_every_stage_metric_has_a_direction():
    b = _bench()
    for stage, (metric, direction) in b.STAGE_METRICS.items():
        assert direction in ("higher", "lower"), stage
        assert isinstance(metric, str) and metric, stage


# ------------------------------------------------------------- backfill


def test_backfill_parses_tail_wrapper_and_refuses_twice(tmp_path):
    repo = tmp_path / "repo"
    repo.mkdir()
    inner = {"metric": "80211a_rx_samples_per_sec_per_chip",
             "numpy_baseline_sps": 5e6, "value": 6.3e8,
             "platform": "tpu", "unit": "samples/s"}
    (repo / "BENCH_r01.json").write_text(json.dumps({
        "n": 1, "rc": 0,
        "tail": "[bench] noise\n" + json.dumps(inner) + "\n"}))
    (repo / "BASELINE.json").write_text(json.dumps({
        "pinned_baseline": {"sps": 6.4e6,
                            "pinned_at": "2026-07-31T22:13:46Z"}}))
    (repo / "BENCH_LIVE.json").write_text(json.dumps({
        "metric": "x", "value": 6.37e8, "platform": "tpu",
        "numpy_baseline_sps": 5.1e6}))
    traj = str(repo / "BENCH_TRAJECTORY.jsonl")
    n, _msg = pr.backfill(traj, repo=str(repo))
    recs = pr.load_trajectory(traj)
    assert n == len(recs) >= 4
    by_stage = {}
    for r in recs:
        assert r["source"].startswith("backfill:")
        by_stage.setdefault(r["stage"], []).append(r)
    vals = {r["value"] for r in by_stage["result"]}
    assert 6.3e8 in vals and 6.37e8 in vals
    assert by_stage["pinned_baseline"][0]["value"] == 6.4e6
    # ISO pinned_at parsed to a real unix stamp, not an ordinal
    assert by_stage["pinned_baseline"][0]["unix"] > 1e9
    # second backfill refuses
    n2, msg2 = pr.backfill(traj, repo=str(repo))
    assert n2 == 0 and "refusing" in msg2
    assert len(pr.load_trajectory(traj)) == len(recs)


def test_committed_trajectory_is_backfilled_and_loadable():
    recs = pr.load_trajectory(pr.DEFAULT_PATH)
    assert any(r["source"].startswith("backfill:") for r in recs), \
        "committed BENCH_TRAJECTORY.jsonl lost its backfilled history"
    # the last good TPU capture must be in the ledger
    assert any(r["platform"] == "tpu" and r["value"] > 1e8
               for r in recs)
    # ... and so must the multichip dryrun history (ISSUE 11): five
    # rounds of (n_devices, blocks_ok), the blocks monotone-growing
    mc = [r for r in recs if r["stage"] == "multichip"]
    assert len(mc) == 10, len(mc)
    blocks = [r["value"] for r in sorted(
        mc, key=lambda r: r["run_id"]) if r["metric"] == "blocks_ok"]
    assert blocks == sorted(blocks) and blocks[0] == 3 \
        and blocks[-1] == 7, blocks


def test_backfill_multichip_family_is_one_shot(tmp_path):
    # the ISSUE 11 satellite: MULTICHIP_r*.json artifacts land in the
    # trajectory exactly once, even when the bench family was already
    # backfilled by an earlier PR — and never twice
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "MULTICHIP_r01.json").write_text(json.dumps({
        "n_devices": 8, "rc": 0, "ok": True, "skipped": False,
        # the "sp not ok" line must NOT count as a passed block (the
        # " ok" substring trap), nor may prose mentioning "okay"
        "tail": "dryrun_multichip(8): dp ok, decoded\n"
                "dryrun_multichip(8): pp ok, pipeline\n"
                "dryrun_multichip(8): sp not ok, halo failed\n"
                "retrying is okay later\n"}))
    (repo / "MULTICHIP_r02.json").write_text(json.dumps({
        "n_devices": 8, "rc": 1, "ok": False, "skipped": True,
        "tail": ""}))                       # skipped round: no record
    traj = str(repo / "traj.jsonl")
    # the bench family is already present (an earlier PR's backfill)
    with open(traj, "w") as f:
        f.write(json.dumps({
            "run_id": "backfill:BENCH_r01", "unix": 1.0,
            "stage": "result", "metric": "rx_sps", "value": 1e8,
            "platform": "tpu", "partial": False,
            "direction": "higher",
            "source": "backfill:BENCH_r01.json"}) + "\n")
    n, msg = pr.backfill(traj, repo=str(repo))
    assert n == 2 and "bench already present" in msg
    recs = [r for r in pr.load_trajectory(traj)
            if r["stage"] == "multichip"]
    assert {(r["metric"], r["value"]) for r in recs} == \
        {("n_devices", 8), ("blocks_ok", 2)}
    assert all(r["platform"] == "cpu"
               and r["source"] == "backfill:MULTICHIP_r01.json"
               for r in recs)
    # second run refuses BOTH families
    n2, msg2 = pr.backfill(traj, repo=str(repo))
    assert n2 == 0 and "refusing" in msg2


# ------------------------------------------------------- trace compare


def _trace(path, p50_ms, n=10, label="rx.stream_chunk"):
    evs = [{"name": label, "ph": "X", "cat": "host", "ts": i * 5000,
            "dur": p50_ms * 1000.0, "pid": 1, "tid": 1}
           for i in range(n)]
    with open(path, "w") as f:
        json.dump({"traceEvents": evs}, f)
    return str(path)


def test_trace_compare_flags_p50_regression(tmp_path, capsys):
    a = _trace(tmp_path / "a.json", 1.0)
    b = _trace(tmp_path / "b.json", 1.5)
    rc = tr.main(["--compare", a, b, "--threshold", "0.2"])
    out = capsys.readouterr().out
    assert rc == 1 and "REGRESSED" in out and "+50.0%" in out
    assert tr.main(["--compare", a, b, "--threshold", "0.9"]) == 0
    # no threshold: informational table, exit 0
    assert tr.main(["--compare", a, b]) == 0


def test_trace_compare_handles_disjoint_labels(tmp_path, capsys):
    a = _trace(tmp_path / "a.json", 1.0, label="only.a")
    b = _trace(tmp_path / "b.json", 1.0, label="only.b")
    assert tr.main(["--compare", a, b, "--threshold", "0.1"]) == 0
    out = capsys.readouterr().out
    assert "only.a" in out and "only.b" in out


def test_trace_report_cost_columns_from_embedded_rider(tmp_path,
                                                       capsys):
    # a trace carrying the observatory's siteCosts + devicePeaks
    # riders grows GB/s and %HBM columns: 1 GB per dispatch at p50 =
    # 1 ms -> 1000 GB/s -> 122.1% of the 819 GB/s v5e peak
    path = tmp_path / "t.json"
    evs = [{"name": "rx.stream_chunk", "ph": "X", "cat": "host",
            "ts": i * 5000, "dur": 1000.0, "pid": 1, "tid": 1}
           for i in range(5)]
    with open(path, "w") as f:
        json.dump({"traceEvents": evs,
                   "siteCosts": {"rx.stream_chunk": {
                       "bytes_accessed": 1e9, "flops": 1e9}},
                   "devicePeaks": {"hbm_gbps": 819.0,
                                   "peak_tflops": 197.0}}, f)
    assert tr.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "GB/s" in out and "%HBM" in out
    assert "1000.00" in out and "122.10" in out


def test_trace_report_costs_file_in_programs_report_shape(tmp_path,
                                                          capsys):
    # --costs accepts the `programs --json` report: label records plus
    # the RESOLVED devicePeaks entry the CLI embeds — %HBM must render
    trace = _trace(tmp_path / "t.json", 1.0)
    rep = {"programs": [{"label": "rx.stream_chunk",
                         "flops": 1e9, "bytes_accessed": 1e9}],
           "device_kind": "TPU v5 lite",
           "devicePeaks": {"hbm_gbps": 819.0, "peak_tflops": 197.0}}
    cpath = tmp_path / "costs.json"
    cpath.write_text(json.dumps(rep))
    assert tr.main([trace, "--costs", str(cpath)]) == 0
    out = capsys.readouterr().out
    assert "%HBM" in out and "122.10" in out
    # a per-kind TABLE (the report's device_peaks catalog) is NOT a
    # usable ceiling and must not crash the report
    rep2 = dict(rep, devicePeaks={"v5e": {"hbm_gbps": 819.0}})
    cpath.write_text(json.dumps(rep2))
    assert tr.main([trace, "--costs", str(cpath)]) == 0
    assert "%HBM" not in capsys.readouterr().out


def test_site_costs_of_normalizes_programs_report():
    rep = {"programs": [
        {"label": "a", "flops": 10.0, "bytes_accessed": 100.0},
        {"label": "a", "flops": 20.0, "bytes_accessed": 200.0},
        {"label": "b", "error": "boom"},
        {"label": "c", "flops": 1.0, "bytes_accessed": 0.0},
    ]}
    costs = tr.site_costs_of(rep)
    assert costs == {"a": {"bytes_accessed": 200.0, "flops": 20.0}}
    bare = {"x": {"bytes_accessed": 5.0, "flops": 1.0}}
    assert tr.site_costs_of(bare) == bare
    assert tr.site_costs_of({"siteCosts": bare}) == bare
