"""BER guard for the windowed Viterbi (docs/windowed_viterbi.md).

A reduced, deterministic version of tools/windowed_ber.py pinning the
two claims the windowing math must keep: at an operating point the
default overlap reproduces the exact decode bit-for-bit, and below the
waterfall the truncation costs no measurable BER. A stitching or
overlap regression breaks these immediately.
"""

import importlib.util
import os

import jax
import numpy as np

from ziria_tpu.ops import viterbi, viterbi_pallas

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "windowed_ber", os.path.join(_REPO, "tools", "windowed_ber.py"))
_wb = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_wb)
_frames = _wb.make_coded_frames     # ONE signal recipe with the study


def _scan_engine(x):
    return jax.vmap(viterbi.viterbi_decode)(x)


def test_operating_snr_identical_default_overlap():
    rng = np.random.default_rng(2026)
    msgs, llrs = _frames(rng, 4, 2048, amp=1.2)
    exact = np.asarray(_scan_engine(llrs))
    win = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=512, _decode=_scan_engine))
    np.testing.assert_array_equal(win, exact)
    # and the code actually works at this point (the claim is about an
    # OPERATING decoder, not a trivially-failing one)
    assert (exact != msgs).mean() < 0.05


def test_below_waterfall_no_ber_penalty():
    rng = np.random.default_rng(7)
    msgs, llrs = _frames(rng, 4, 2048, amp=0.9)
    exact = np.asarray(_scan_engine(llrs))
    win = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=512, _decode=_scan_engine))
    ber_e = (exact != msgs).mean()
    ber_w = (win != msgs).mean()
    # individual bits may differ, but the error RATE must not move
    # beyond statistical noise (measured margin ~1e-3; allow 2e-2 rel)
    assert abs(ber_w - ber_e) < 0.02 * max(ber_e, 1e-9) + 2e-3
