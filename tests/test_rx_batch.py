"""Batched RX decode (Pallas Viterbi fast path) vs the per-frame path."""

import numpy as np
import jax.numpy as jnp
import pytest

from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import RATES, n_symbols
from ziria_tpu.utils.bits import bytes_to_bits


@pytest.mark.parametrize("mbps", [6, 54])
def test_decode_data_batch_matches_static(mbps):
    rate = RATES[mbps]
    n_bytes = 60
    n_sym = n_symbols(n_bytes, rate)
    rng = np.random.default_rng(mbps)
    frames, wants = [], []
    for _ in range(3):
        psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
        frames.append(np.asarray(tx.encode_frame(psdu, mbps)))
        wants.append(np.asarray(bytes_to_bits(psdu)))
    fb = jnp.asarray(np.stack(frames))

    psdu_b, svc_b = rx.decode_data_batch(fb, rate, n_sym, 8 * n_bytes)
    for k in range(3):
        ps, sv = rx.decode_data_static(fb[k], rate, n_sym, 8 * n_bytes)
        np.testing.assert_array_equal(np.asarray(psdu_b)[k], np.asarray(ps))
        np.testing.assert_array_equal(np.asarray(svc_b)[k], np.asarray(sv))
        np.testing.assert_array_equal(np.asarray(psdu_b)[k], wants[k])
