"""Seeded pipeline fuzzing: random combinator programs must agree
across every executor — interpreter oracle, fused jit, jit+fold, and
the 8-way stream-parallel path (every generated stage is stateless or
declares advance/memory, so sp is always legal). This automates the
reference's flag-matrix discipline (SURVEY.md §4) over a program space
instead of a hand-picked corpus; failures print the seed for replay."""

import jax.numpy as jnp
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.core.opt import fold
from ziria_tpu.interp.interp import run
from ziria_tpu.parallel.streampar import (StreamParError, stream_mesh,
                                          stream_parallel)

N_CASES = 24


def _rand_stage(rng: np.random.Generator):
    """One random stage (stateless, or stateful with advance/memory)."""
    kind = rng.choice(["affine", "mod", "sum4", "expand", "clip",
                       "ctr", "fir"])
    if kind == "affine":
        a, b = int(rng.integers(1, 5)), int(rng.integers(-3, 4))
        return z.zmap(lambda x, _a=a, _b=b: x * _a + _b,
                      name=f"affine{a}_{b}")
    if kind == "mod":
        m = int(rng.integers(3, 200))
        return z.zmap(lambda x, _m=m: x % _m, name=f"mod{m}")
    if kind == "sum4":
        return z.zmap(lambda v: jnp.sum(v), in_arity=4, out_arity=1,
                      name="sum4")
    if kind == "expand":
        return z.zmap(lambda x: jnp.stack([x, -x]), in_arity=1,
                      out_arity=2, name="expand")
    if kind == "clip":
        lo, hi = -int(rng.integers(5, 60)), int(rng.integers(5, 60))
        return z.zmap(lambda x, _l=lo, _h=hi: jnp.clip(x, _l, _h),
                      name=f"clip{lo}_{hi}")
    if kind == "ctr":
        s0 = int(rng.integers(0, 7))
        return z.map_accum(lambda s, x: (s + 1, x + s), s0,
                           name=f"ctr{s0}",
                           advance=lambda s, n: s + n)
    # fir: finite-memory delay line
    k = int(rng.integers(2, 6))

    def step(s, x, _k=k):
        s2 = jnp.concatenate([s[1:], jnp.asarray(x, jnp.int32)[None]])
        return s2, jnp.sum(s2)

    return z.map_accum(step, np.zeros(k, np.int32), name=f"fir{k}",
                       memory=k)


def _rand_pipeline(seed: int):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 5))
    stages = [_rand_stage(rng) for _ in range(n)]
    comp = stages[0] if n == 1 else z.pipe(*stages)
    n_items = int(rng.integers(50, 2500))
    xs = rng.integers(-100, 100, n_items).astype(np.int64)
    return comp, xs


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_executor_agreement(seed):
    comp, xs = _rand_pipeline(seed)
    want = run(comp, list(xs)).out_array()
    got_jit = np.asarray(run_jit(comp, xs))
    got_fold = np.asarray(run_jit(fold(comp), xs))

    # the jit tail policy drops sub-iteration remainders at EOF; the
    # interpreter oracle may emit partial-iteration output — compare on
    # the jit-produced prefix, which must be a prefix of the oracle's
    want = np.asarray(want)
    assert got_jit.shape[0] <= want.shape[0], (
        f"seed {seed}: jit produced MORE than the oracle")
    np.testing.assert_array_equal(
        got_jit, want[: got_jit.shape[0]], err_msg=f"seed {seed} (jit)")
    np.testing.assert_array_equal(
        got_fold, got_jit, err_msg=f"seed {seed} (fold)")

    # stream-parallel must equal plain jit exactly (same tail policy)
    try:
        got_sp = np.asarray(stream_parallel(comp, xs, stream_mesh(8)))
    except StreamParError as e:  # pragma: no cover - generator bug
        pytest.fail(f"seed {seed}: stream_parallel refused: {e}")
    np.testing.assert_array_equal(
        got_sp, got_jit, err_msg=f"seed {seed} (sp)")

    # auto-pipelined placement across 2 devices must also agree (on
    # its exact-macro-chunk prefix; fill/drain handles the rest)
    stages = ir.pipeline_stages(comp)
    if len(stages) >= 2:
        import jax

        from ziria_tpu.parallel.autosplit import auto_pipeline
        from ziria_tpu.parallel.stages import lower_stage_parallel
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("pp",))
        pp = lower_stage_parallel(
            auto_pipeline(comp, 2), mesh,
            in_item=jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype),
            width=2)
        m = xs.shape[0] // pp.take
        if m:
            ys = np.asarray(
                pp.run(xs[: m * pp.take].reshape(
                    (m, pp.take) + xs.shape[1:])))
            flat = ys.reshape((m * pp.emit,) + ys.shape[2:])
            np.testing.assert_array_equal(
                flat, got_jit[: flat.shape[0]],
                err_msg=f"seed {seed} (pp)")
