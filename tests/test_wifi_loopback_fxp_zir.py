"""The ALL-INTEGER in-language loopback (examples/wifi_loopback_fxp.zir):
fcs_add >>> tx_frame_fxp >>> rx_fxp under --fxp-complex16 — no floating
point touches a sample on either side, the discipline the reference's
SORA-backed PHY ran end to end. Payload in must equal payload out, and
the fixed-point transmitter's air signal must be standard-compliant
(the FLOAT library receiver decodes it too)."""

import os

import numpy as np
import pytest

from ziria_tpu.backend import hybrid as H
from ziria_tpu.frontend import compile_file, compile_source
from ziria_tpu.interp.interp import run
from ziria_tpu.phy.wifi import rx
from ziria_tpu.utils.bits import bytes_to_bits

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")
SRC = os.path.join(EXAMPLES, "wifi_loopback_fxp.zir")


def _frames(pairs, seed):
    rng = np.random.default_rng(seed)
    stream, want = [], []
    for rate, n_bytes in pairs:
        bits = rng.integers(0, 2, 8 * n_bytes).astype(np.int32)
        stream += [rate, n_bytes] + bits.tolist()
        want.append(bits.astype(np.uint8))
    return [np.int32(v) for v in stream], np.concatenate(want)


def test_loopback_fxp_two_frames_interp():
    prog = compile_file(SRC, fxp_complex16=True)
    xs, want = _frames(((12, 25), (54, 40)), seed=400)
    got = np.asarray(run(prog.comp, xs).out_array(), np.uint8)
    np.testing.assert_array_equal(got, want)


def test_loopback_fxp_hybrid_matches_interp():
    prog = compile_file(SRC, fxp_complex16=True)
    hyb = H.hybridize(prog.comp)
    xs, want = _frames(((24, 30), (48, 35)), seed=401)
    gi = np.asarray(run(prog.comp, xs).out_array(), np.uint8)
    gh = np.asarray(run(hyb, xs).out_array(), np.uint8)
    np.testing.assert_array_equal(gi, want)
    np.testing.assert_array_equal(gh, want)


def test_loopback_fxp_random_rate_length_fuzz():
    """Randomized rate/length mix through the ALL-INTEGER loopback:
    every payload must come back exactly (the TX-fuzz discipline of
    test_wifi_tx_rates_zir applied to the integer chain)."""
    rng = np.random.default_rng(360)
    rates = [6, 9, 12, 18, 24, 36, 48, 54]
    pairs = [(int(rng.choice(rates)), int(rng.integers(10, 60)))
             for _ in range(5)]
    xs, want = _frames(pairs, seed=361)
    prog = compile_file(SRC, fxp_complex16=True)
    got = np.asarray(run(prog.comp, xs).out_array(), np.uint8)
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("rate", [6, 18, 36, 54])
def test_fxp_tx_air_signal_decodes_under_float_receiver(rate):
    """Cross-family compliance: the integer transmitter's wire signal
    is a standard 802.11a frame the f32 LIBRARY receiver decodes."""
    src = ('#include "lib/wifi_tx_fxp_lib.zir"\n\n'
           'let comp main = read[int32] >>> repeat { tx_frame_fxp() }'
           ' >>> write[complex16]\n')
    prog = compile_source(src, src_name="tx_fxp_probe",
                          base_dir=EXAMPLES, fxp_complex16=True)
    rng = np.random.default_rng(410 + rate)
    n = 40
    psdu = rng.integers(0, 256, n).astype(np.uint8)
    bits = np.asarray(bytes_to_bits(psdu)).astype(np.int32)
    xs = [np.int32(v) for v in [rate, n] + bits.tolist()]
    x = np.asarray(run(prog.comp, xs).out_array(), np.float32)
    r = rx.receive(np.concatenate(
        [np.zeros((50, 2), np.float32), x / 512.0]))
    assert r.ok and r.rate_mbps == rate
    np.testing.assert_array_equal(r.psdu_bits,
                                  np.asarray(bytes_to_bits(psdu)))
