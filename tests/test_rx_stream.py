"""Device-resident streaming receiver (backend/framebatch.receive_stream
+ rx.stream_chunk_graph + ops/sync.locate_frames): a long multi-frame
I/Q stream decoded in O(chunks) device dispatches (<= 2 per chunk),
with every emitted frame bit-identical — RxResult field for field,
FCS status included — to slicing `stream[start : start + frame_len]`
out and calling per-capture `rx.receive` on it, and every emitted
start hitting the synthesizer's ground truth.

Budget discipline (the tier-1 870 s cutoff is real): ONE module
fixture pays the streaming geometry compiles — chunk 4096, window
1024, K=8 candidate lanes, 8-symbol decode bucket, the same
1024-sample capture bucket / 8-symbol geometry the batched-acquire
and mixed-dispatch suites share — and every test is a cheap
re-dispatch. The edge-case streams (straddle, minimum gap, overflow,
all-noise) are all constructed AT the fixture geometry so no test
compiles a second chunk graph.
"""

import numpy as np
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import link
from ziria_tpu.phy.wifi import rx
from ziria_tpu.phy.wifi.params import RATES
from ziria_tpu.utils import dispatch

N_BYTES = 12     # +4 FCS = the suite's standard 16-byte on-air PSDU:
                 # every frame fits the 1024-sample window (6 Mbps =
                 # 960 samples) and the decode bucket stays 8 symbols
CHUNK, FRAME_LEN, K = 4096, 1024, 8
GEO = dict(chunk_len=CHUNK, frame_len=FRAME_LEN, max_frames_per_chunk=K,
           check_fcs=True)


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


def _oracle(stream, start):
    """THE identity contract: per-capture receive over the stream
    sliced at the (true/reported) frame start."""
    return rx.receive(stream[start: start + FRAME_LEN], check_fcs=True)


@pytest.fixture(scope="module")
def corpus():
    """All 8 rates on one continuous stream — random gaps, CFO,
    initial delay, AWGN, FCS appended — plus one streaming and one
    per-capture-mode pass with dispatch counters."""
    rng = np.random.default_rng(20260804)
    mbps = sorted(RATES)
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in mbps]
    stream, starts = link.stream_many(
        psdus, mbps, snr_db=30.0, cfo=1e-4, delay=60, seed=5,
        add_fcs=True, tail=FRAME_LEN)
    with dispatch.count_dispatches() as d_st:
        got_s, st_s = framebatch.receive_stream(stream, streaming=True,
                                                **GEO)
    with dispatch.count_dispatches() as d_pc:
        got_p, st_p = framebatch.receive_stream(stream, streaming=False,
                                                **GEO)
    return stream, starts, got_s, st_s, d_st, got_p, st_p, d_pc


def test_all_8_rates_bit_identical_to_sliced_receive(corpus):
    # the acceptance contract: reported starts == the synthesizer's
    # TRUE frame starts, and every RxResult (crc_ok included) equals
    # per-capture receive over the stream sliced at that start
    stream, starts, got_s, _st, _d, _gp, _sp, _dp = corpus
    assert [f.start for f in got_s] == list(starts)
    for f in got_s:
        ref = _oracle(stream, f.start)
        assert f.result.ok and ref.ok and f.result.crc_ok
        assert _same_result(f.result, ref)
    assert sorted(f.result.rate_mbps for f in got_s) == sorted(RATES)


def test_percapture_mode_is_the_same_oracle(corpus):
    # the opt-out path (--no-streaming-rx) stays available and stays
    # exact: same detected windows, per-capture receive per frame
    _s, _starts, got_s, _st, _d, got_p, _sp, _dp = corpus
    assert [f.start for f in got_p] == [f.start for f in got_s]
    for a, b in zip(got_p, got_s):
        assert _same_result(a.result, b.result)


def test_o_chunks_dispatches_vs_o_frames(corpus):
    # the tentpole number: <= 2 dispatches per CHUNK (scan + decode)
    # however many frames ride the stream, vs >= 3 per FRAME (+ the
    # per-chunk scan) for the per-capture path
    _s, starts, _gs, st_s, d_st, _gp, st_p, d_pc = corpus
    n = len(starts)
    assert st_s.chunks >= 2                   # the stream really chunks
    assert d_st.total <= 2 * st_s.chunks, dict(d_st.counts)
    assert d_st.counts["rx.stream_chunk"] == st_s.chunks
    assert d_st.counts["rx.stream_decode"] <= st_s.chunks
    assert d_pc.total >= 3 * n + 1, dict(d_pc.counts)
    # double-buffering really overlapped: chunk i+1 was in flight
    # before chunk i drained (the utils/dispatch gauge)
    assert d_st.gauges["rx.stream_inflight"] == 2
    assert st_s.max_in_flight == 2
    assert st_s.overflow_chunks == 0


def test_boundary_straddling_frame_decoded_exactly_once(corpus):
    """A frame whose samples cross the chunk boundary is owned by
    exactly one chunk (the next one, which contains it fully inside
    the overlap) — decoded once, bit-identically."""
    stream0, _starts, _gs, _st, _d, _gp, _sp, _dp = corpus
    rng = np.random.default_rng(9)
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in range(2)]
    # 54 Mbps frames are 480 samples on air; gap 3260 puts frame 1 at
    # 60 + 480 + 3260 = 3800: inside chunk 0's overlap region
    # [3072, 4096) and spanning the 4096 boundary into chunk 1
    stream, starts = link.stream_many(
        psdus, [54, 54], gaps=[3260], snr_db=30.0, cfo=1e-4, delay=60,
        seed=6, add_fcs=True, tail=FRAME_LEN)
    assert starts[1] == 3800 and starts[1] + 480 > CHUNK
    got, stats = framebatch.receive_stream(stream, **GEO)
    assert [f.start for f in got] == list(starts)     # exactly once
    for f in got:
        assert f.result.ok and f.result.crc_ok
        assert _same_result(f.result, _oracle(stream, f.start))
    assert stats.chunks == 2


def test_back_to_back_frames_at_minimum_gap(corpus):
    """Two longest frames nose to tail (10-sample gap): the dead-zone
    suppression must not eat the second frame, and each window must
    time onto its OWN preamble."""
    rng = np.random.default_rng(10)
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in range(2)]
    stream, starts = link.stream_many(
        psdus, [6, 6], gaps=[10], snr_db=30.0, cfo=1e-4, delay=60,
        seed=7, add_fcs=True, tail=FRAME_LEN)
    assert starts[1] - starts[0] == 970       # 960-sample frame + 10
    got, _stats = framebatch.receive_stream(stream, **GEO)
    assert [f.start for f in got] == list(starts)
    for f in got:
        assert f.result.ok and f.result.crc_ok
        assert _same_result(f.result, _oracle(stream, f.start))


def test_overflow_reported_not_silently_dropped(corpus):
    """More than K eligible plateaus in one chunk's owned region:
    the K extracted lanes emit, the surplus raises the chunk's
    overflow flag (StreamStats.overflow_chunks) — never a silent
    drop. Built from bare 320-sample preambles at the FIXTURE
    geometry so no new graph compiles."""
    stream0, starts0, _gs, _st, _d, _gp, _sp, _dp = corpus
    pre = stream0[int(starts0[0]): int(starts0[0]) + 320]   # STS+LTS
    rng = np.random.default_rng(11)
    stream = rng.normal(scale=0.01, size=(CHUNK + 512, 2)) \
        .astype(np.float32)
    for i in range(9):                        # 9 plateaus, K = 8
        stream[i * 360: i * 360 + 320] += pre
    got, stats = framebatch.receive_stream(stream, **GEO)
    assert stats.overflow_chunks >= 1
    assert len(got) <= K
    # the K extracted lanes still honor the identity contract
    for f in got:
        assert _same_result(f.result, _oracle(stream, f.start))


def test_failure_lanes_bit_identical(corpus):
    """Failure lanes on the STREAM honor the identity contract too:
    a frame whose SIGNAL parity is corrupted (detected, then
    classified ACQ_FAIL) and a frame the stream ends in the middle of
    (ACQ_TRUNCATED through the final chunk's traced own-bucket cap)
    both emit the exact fail RxResult per-capture receive returns."""
    import jax.numpy as jnp

    from ziria_tpu.ops import coding, interleave, modulate, ofdm
    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(13)
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in range(3)]
    # no noise/CFO so the SIGNAL patch below is sample-exact
    stream, starts = link.stream_many(
        psdus, [24, 24, 24], gaps=[400, 400], snr_db=np.inf, cfo=0.0,
        delay=60, seed=14, add_fcs=True, tail=FRAME_LEN)
    # frame 1's SIGNAL re-encoded with its even-parity bit flipped
    # (the test_rx_batched_acquire recipe), patched over the stream
    sig_bits = np.array(tx.signal_field_bits(RATES[24], N_BYTES + 4))
    sig_bits[17] ^= 1
    coded = coding.conv_encode(jnp.asarray(sig_bits))
    syms = modulate.modulate(interleave.interleave(coded, 48, 1), 1)
    bins = ofdm.map_subcarriers(syms[None, :, :], symbol_index0=0)
    s1 = int(starts[1])
    stream[s1 + 320: s1 + 400] = np.asarray(ofdm.ofdm_modulate(bins)[0])
    # ...and the stream ends 500 samples into frame 2 (mid-DATA)
    stream = stream[: int(starts[2]) + 500]

    got, _stats = framebatch.receive_stream(stream, **GEO)
    assert [f.start for f in got] == list(starts)
    for f in got:
        assert _same_result(f.result, _oracle(stream, f.start))
    assert got[0].result.ok and got[0].result.crc_ok
    assert not got[1].result.ok and got[1].result.rate_mbps == 0
    assert not got[2].result.ok and got[2].result.rate_mbps == 24 \
        and got[2].result.length_bytes == N_BYTES + 4      # truncated


def test_stream_head_truncated_preamble_not_silently_dropped(corpus):
    """A stream that begins mid-preamble: the LTS alignment lands
    BELOW 0, which on any later chunk means 'previous chunk's frame'
    — but on the stream's FIRST chunk there is no previous chunk, so
    the start clamps to 0 (exactly per-capture locate_frame's
    max(lts1-192, 0) clamp) and a result is emitted, identical to
    receive over the stream head. Never a silent drop."""
    rng = np.random.default_rng(15)
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in range(2)]
    full, starts = link.stream_many(
        psdus, [24, 54], gaps=[400], snr_db=30.0, cfo=1e-4, delay=0,
        seed=16, add_fcs=True, tail=FRAME_LEN)
    stream = full[40:]                 # first 40 preamble samples lost
    got, _stats = framebatch.receive_stream(stream, **GEO)
    # the head-truncated frame emits at the clamped start 0; frame 1
    # is intact at its shifted true start
    assert [f.start for f in got] == [0, int(starts[1]) - 40]
    for f in got:
        assert _same_result(f.result, _oracle(stream, f.start))
    assert got[1].result.ok and got[1].result.crc_ok


def test_deferred_overlap_plateau_is_not_overflow(corpus):
    """K plateaus owned by the chunk plus one more in the DEFERRED
    overlap region: the leftover is the next chunk's frame, not a
    drop, so the overflow flag must stay clear (the overflow scan is
    capped at the ownership bound) — and the deferred plateau still
    gets its own candidate in the next chunk."""
    stream0, starts0, _gs, _st, _d, _gp, _sp, _dp = corpus
    pre = stream0[int(starts0[0]): int(starts0[0]) + 320]
    rng = np.random.default_rng(16)
    stream = rng.normal(scale=0.01, size=(CHUNK + 2048, 2)) \
        .astype(np.float32)
    for i in range(8):                        # exactly K owned
        stream[i * 360: i * 360 + 320] += pre
    # deferred plateau, past the stride AND past the overflow scan's
    # 224-sample alignment-slack sliver (which stays conservative:
    # a surplus frame THIS chunk owns must always flag)
    stream[3400: 3720] += pre
    got, stats = framebatch.receive_stream(stream, **GEO)
    assert stats.overflow_chunks == 0
    assert any(f.start >= 3072 for f in got)  # next chunk took it
    for f in got:
        assert _same_result(f.result, _oracle(stream, f.start))


def test_all_noise_chunks_cost_one_dispatch_each(corpus):
    rng = np.random.default_rng(12)
    stream = rng.normal(scale=0.05, size=(2 * CHUNK, 2)) \
        .astype(np.float32)
    with dispatch.count_dispatches() as d:
        got, stats = framebatch.receive_stream(stream, **GEO)
    assert got == []
    assert stats.frames == 0 and stats.overflow_chunks == 0
    # no decodable lane -> the decode dispatch never fires
    assert d.total == stats.chunks
    assert d.counts.get("rx.stream_decode", 0) == 0


def test_push_flush_carry_threads_across_slabs(corpus):
    """The push-driven surface: the same stream fed in ragged slabs
    through StreamReceiver emits the same frames as the one-shot
    call, with the (tail, offset, emitted) carry threading across
    chunk boundaries. The whole steady state runs under
    dispatch.no_recompile — the runtime twin of the jaxlint R1
    cache-key rule: at the fixture's already-compiled geometry, ragged
    pushes may only RE-DISPATCH the two compiled chunk programs, never
    mint a fresh compile-cache entry."""
    stream, starts, got_s, _st, _d, _gp, _sp, _dp = corpus
    with dispatch.no_recompile(rx._jit_stream_chunk,
                               rx._jit_stream_decode):
        sr = framebatch.StreamReceiver(**GEO)
        got = []
        cuts = [0, 777, 3000, 4100, 9001, stream.shape[0]]
        for a, b in zip(cuts, cuts[1:]):
            got += sr.push(stream[a:b])
        assert sr.carry.offset + sr.carry.tail.shape[0] \
            == stream.shape[0]
        got += sr.flush()
    assert sr.carry.emitted == len(got)
    assert [f.start for f in got] == [f.start for f in got_s]
    for a, b in zip(got, got_s):
        assert _same_result(a.result, b.result)
    with pytest.raises(RuntimeError):
        sr.push(stream[:8])                   # closed stream


def test_stream_bucket_graph_matches_host_rule():
    # the traced per-lane detector cap must be THE _stream_bucket rule
    # (the acquire_many limit contract hangs off it)
    import jax.numpy as jnp
    nv = np.arange(1, FRAME_LEN + 1, dtype=np.int32)
    got = np.asarray(rx._stream_bucket_graph(jnp.asarray(nv), FRAME_LEN))
    want = np.asarray([rx._stream_bucket(int(v)) for v in nv])
    np.testing.assert_array_equal(got, want)


def test_locate_frames_k1_matches_single_frame_oracle(corpus):
    # the K=1 oracle relationship the sync docstrings name: one frame
    # per capture -> locate_frames' first lane finds the exact start
    # locate_frame's global peak-pick reports
    stream, starts, _gs, _st, _d, _gp, _sp, _dp = corpus
    from ziria_tpu.ops import sync
    cap = stream[int(starts[0]) - 40: int(starts[0]) - 40 + FRAME_LEN]
    d1, s1, _e = sync.locate_frame(cap)
    fk, sk, ovf = sync.locate_frames(cap, 1)
    assert bool(d1) and bool(np.asarray(fk)[0])
    assert int(np.asarray(sk)[0]) == int(s1) == 40
    assert not bool(ovf)


def test_streaming_rx_env_knob(monkeypatch):
    # the CLI's scoped-env pattern: default ON, ZIRIA_STREAMING_RX=0
    # forces the per-capture oracle, an explicit argument wins
    monkeypatch.delenv("ZIRIA_STREAMING_RX", raising=False)
    assert framebatch.streaming_rx_enabled(None)
    monkeypatch.setenv("ZIRIA_STREAMING_RX", "0")
    assert not framebatch.streaming_rx_enabled(None)
    assert framebatch.streaming_rx_enabled(True)
    monkeypatch.setenv("ZIRIA_STREAMING_RX", "1")
    assert framebatch.streaming_rx_enabled(None)
    assert not framebatch.streaming_rx_enabled(False)


def test_bad_geometry_rejected():
    with pytest.raises(ValueError):
        framebatch.StreamReceiver(chunk_len=4096, frame_len=1000)
    with pytest.raises(ValueError):
        framebatch.StreamReceiver(chunk_len=1024, frame_len=1024)
    # zero frames + finite SNR: no frame power to reference — an
    # explicit error, never a silent all-zero "noise" stream
    with pytest.raises(ValueError):
        link.stream_many([], [], snr_db=10.0)
    stream, starts = link.stream_many([], [], tail=600)
    assert stream.shape == (600, 2) and starts.size == 0
