"""AutoLUT *inference* (frontend/lutinfer.py — the reference's
LUTAnalysis role, SURVEY.md §2.1): pure surface functions with small
total input bit-width are auto-detected and tabulated, both in `map f`
position (packed multi-bit items like `arr[8] bit`) and at expression
call sites staged under jit (`--autolut`). The flag-invariance
discipline applies: LUT'd and direct programs must agree exactly."""

import numpy as np
import pytest

from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.core.autolut import autolut
from ziria_tpu.frontend import compile_source
from ziria_tpu.frontend import lutinfer
from ziria_tpu.interp.interp import run


def _maps(comp):
    out = []

    def walk(c):
        if isinstance(c, ir.Map):
            out.append(c)
        ir.map_children(c, lambda ch, _b: (walk(ch), ch)[1])

    walk(comp)
    return out


PACK8 = """
fun pack8(b: arr[8] bit) : int8 {
  var v: int8 := 0;
  for i in [0, 8] { v := v + (int8(b[i]) << int8(i)) }
  return v
}
let comp main = read[bit] >>> map pack8 >>> write[int8]
"""


def test_map_arr_bit_inferred_and_exact():
    prog = compile_source(PACK8)
    m = [m for m in _maps(prog.comp) if m.label() == "pack8"]
    assert m and m[0].in_domain is None and m[0].lut is not None
    assert m[0].lut.domain == 256
    xs = np.random.default_rng(0).integers(0, 2, 8 * 32).astype(np.uint8)
    want = np.asarray(run_jit(prog.comp, xs))
    lutted = autolut(prog.comp)
    assert any(mm.label().startswith("lut[") for mm in _maps(lutted))
    got = np.asarray(run_jit(lutted, xs))
    np.testing.assert_array_equal(got, want)
    # interpreter on the LUT'd program agrees too
    got_i = run(lutted, list(xs)).out_array()
    np.testing.assert_array_equal(np.asarray(got_i), want)


def test_map_int16_inferred_domain():
    prog = compile_source("""
      fun nib(x: int16) : int16 { return (x >> int16(4)) & int16(0xF) }
      let comp main = read[int16] >>> map nib >>> write[int16]
    """)
    m = [m for m in _maps(prog.comp) if m.label() == "nib"][0]
    assert m.lut is not None and m.lut.domain == 65536


MIX = """
fun mix(x: int8, b: bit) : int8 {
  var r: int8 := x + int8(3);
  if b == 1 then { r := x ^ int8(0x5A) }
  return r
}
let comp main = read[int8]
  >>> repeat { x <- take; b <- take; emit mix(int8(x), bit(b & 1)) }
  >>> write[int8]
"""


def test_expr_call_lut_matches_direct():
    xs = np.random.default_rng(1).integers(-128, 128, 64).astype(np.int8)
    direct = compile_source(MIX)
    lut = compile_source(MIX, autolut=True)
    want = np.asarray(run_jit(direct.comp, xs))
    got = np.asarray(run_jit(lut.comp, xs))
    np.testing.assert_array_equal(got, want)


RETIF = """
fun sel(x: int8, b: bit) : int8 {
  if b == 1 then { return x ^ int8(0x5A) } else { return x + int8(3) }
}
let comp main = read[int8]
  >>> repeat { x <- take; b <- take; emit sel(int8(x), bit(b & 1)) }
  >>> write[int8]
"""


def test_lut_enables_return_in_dynamic_if():
    # `return` inside a data-dependent if cannot stage under jit — but
    # the LUT build's concrete-evaluation fallback sidesteps staging
    # entirely (as the reference's compile-time LUT generation did), so
    # with --autolut the program compiles and matches the interpreter
    from ziria_tpu.frontend.eval import ZiriaRuntimeError
    xs = np.random.default_rng(2).integers(-128, 128, 64).astype(np.int8)
    direct = compile_source(RETIF)
    with pytest.raises(ZiriaRuntimeError):
        run_jit(direct.comp, xs)
    want = run(direct.comp, list(xs)).out_array()   # interpreter oracle
    lut = compile_source(RETIF, autolut=True)
    got = np.asarray(run_jit(lut.comp, xs))
    np.testing.assert_array_equal(got, np.asarray(want))


def test_expr_call_lut_table_actually_used():
    lut = compile_source(MIX, autolut=True)
    xs = np.arange(-16, 16, dtype=np.int8)
    run_jit(lut.comp, xs)
    # find the Ctx through the elaborated map closure is awkward; the
    # spec memo lives on the program's shared Ctx — reach it via any
    # FunDef captured in a Map/closure is not exposed, so recompile and
    # drive the evaluator directly instead
    from ziria_tpu.frontend.elab import Elaborator
    from ziria_tpu.frontend.parser import parse_program
    el = Elaborator(parse_program(MIX, "<mix>"), "<mix>",
                    autolut=True)
    cp = el.build("main")
    run_jit(cp.comp, xs)
    assert "mix" in el.ctx.lut_tables          # table built
    assert el.ctx.lut_specs["mix"] is not None  # verdict memoized
    tab = el.ctx.lut_tables["mix"]
    assert tab.shape[0] == 512                 # 8 + 1 bits packed


def test_static_args_stay_direct():
    # all-static calls fold at elaboration; no table should be built
    from ziria_tpu.frontend.elab import Elaborator
    from ziria_tpu.frontend.parser import parse_program
    el = Elaborator(parse_program(MIX, "<mix>"), "<mix>", autolut=True)
    el.build("main")
    assert "mix" not in el.ctx.lut_tables


@pytest.mark.parametrize("src,reason", [
    ("""
     fun shout(x: int8) : int8 { println "x"; return x }
     let comp main = read[int8] >>> map shout >>> write[int8]
     """, "print is impure"),
    ("""
     fun wide(x: int32) : int32 { return x + 1 }
     let comp main = read[int32] >>> map wide >>> write[int32]
     """, "int32 exceeds the bit-width cap"),
    ("""
     fun big(b: arr[24] bit) : int32 { return 1 }
     let comp main = read[bit] >>> map big >>> write[int32]
     """, "24 bits > MAX_LUT_BITS"),
])
def test_not_lutable(src, reason):
    prog = compile_source(src)
    for m in _maps(prog.comp):
        assert m.lut is None, reason


def test_recursive_fun_rejected():
    # no surface recursion exists (funs see only earlier decls), so
    # drive the analysis directly with a self-calling body
    from ziria_tpu.frontend.elab import Elaborator
    from ziria_tpu.frontend.parser import parse_program
    el = Elaborator(parse_program("""
      fun f(x: int8) : int8 { return f(x) }
      let comp main = read[int8] >>> map f >>> write[int8]
    """, "<rec>"), "<rec>")
    el.elaborate()
    fd = el.ctx.funs["f"]
    assert lutinfer.spec_for_fun("f", fd, el.ctx) is None


def test_closure_constant_baked():
    src = """
    let key = 0x33
    fun enc(x: int8) : int8 { return x ^ int8(key) }
    let comp main = read[int8] >>> map enc >>> write[int8]
    """
    prog = compile_source(src)
    m = [m for m in _maps(prog.comp) if m.label() == "enc"][0]
    # int8 scalar params already carry a declared in_domain (round-1
    # path); the closure-constant read must not block LUT-ability when
    # the analysis is consulted directly
    from ziria_tpu.frontend.elab import Elaborator
    from ziria_tpu.frontend.parser import parse_program
    el = Elaborator(parse_program(src, "<enc>"), "<enc>")
    el.elaborate()
    spec = lutinfer.spec_for_fun("enc", el.ctx.funs["enc"], el.ctx)
    assert spec is not None and spec.domain == 256
    xs = np.arange(-128, 128, dtype=np.int8)
    want = np.asarray(run_jit(prog.comp, xs))
    got = np.asarray(run_jit(autolut(prog.comp), xs))
    np.testing.assert_array_equal(got, want)


def test_oversize_output_table_falls_back_to_direct_call():
    # 16-bit domain passes the bit cap, but x 512-element output rows
    # the table would exceed MAX_TABLE_ITEMS — the call site must fall
    # back to the direct call (and memoize the refusal), not bake a
    # multi-MB constant into the graph
    src = """
    fun spread(x: int16) : arr[512] int16 {
      var v: arr[512] int16;
      for i in [0, 512] { v[i] := x + int16(i) }
      return v
    }
    let comp main = read[int16]
      >>> repeat { x <- take; emits spread(int16(x)) }
      >>> write[int16]
    """
    from ziria_tpu.frontend.elab import Elaborator
    from ziria_tpu.frontend.parser import parse_program
    el = Elaborator(parse_program(src, "<sp>"), "<sp>", autolut=True)
    cp = el.build("main")
    xs = np.array([1, 2], np.int16)
    out = np.asarray(run_jit(cp.comp, xs))
    want = np.concatenate([v + np.arange(512) for v in xs]).astype(np.int16)
    np.testing.assert_array_equal(out, want)
    assert "spread" not in el.ctx.lut_tables
    assert el.ctx.lut_specs.get("spread", "absent") is None  # memoized no


def test_oversize_map_left_unlutted():
    # same oversize function in `map` position: the autolut pass must
    # leave the map un-LUT'd (instant upfront refusal), not crash
    src = """
    fun spread(x: int16) : arr[512] int16 {
      var v: arr[512] int16;
      for i in [0, 512] { v[i] := x + int16(i) }
      return v
    }
    let comp main = read[int16] >>> map spread >>> write[int16]
    """
    prog = compile_source(src)
    m = [m for m in _maps(prog.comp) if m.label() == "spread"][0]
    assert m.lut is not None                    # inferred LUT-able...
    lutted = autolut(prog.comp)
    labels = [mm.label() for mm in _maps(lutted)]
    assert "spread" in labels                   # ...but left direct
    assert not any(l.startswith("lut[") for l in labels)


def test_unstageable_big_domain_left_direct():
    # return-inside-dynamic-if + 16-bit domain: too big for the
    # concrete per-row fallback, unstageable for the vmap build — the
    # autolut pass must leave the map un-LUT'd (program still works
    # exactly as without the flag), not crash the compile
    src = """
    fun sel16(x: int16) : int16 {
      if x > 0 then { return x } else { return 0 - x }
    }
    let comp main = read[int16] >>> map sel16 >>> write[int16]
    """
    prog = compile_source(src)
    lutted = autolut(prog.comp)              # must not raise
    labels = [m.label() for m in _maps(lutted)]
    assert "sel16" in labels and not any(
        l.startswith("lut[") for l in labels)
    xs = np.array([-5, -1, 0, 7], np.int16)
    out = run(lutted, list(xs)).out_array()
    np.testing.assert_array_equal(np.asarray(out), np.abs(xs))


def test_bool_param_nonzero_semantics():
    # bool packs as (v != 0), matching cast_value — a traced int 2 must
    # hit the True row, exactly like the direct call would
    src = """
    fun pick(x: int8, b: bool) : int8 {
      var r : int8 := 0 - x;
      if b then { r := x };
      return r
    }
    let comp main = read[int8]
      >>> repeat { x <- take; emit pick(int8(x), bool(x & 2)) }
      >>> write[int8]
    """
    xs = np.array([0, 1, 2, 3, 6, -2], np.int8)
    want = np.asarray(run_jit(compile_source(src).comp, xs))
    got = np.asarray(run_jit(compile_source(src, autolut=True).comp, xs))
    np.testing.assert_array_equal(got, want)


def test_multiarg_packing_roundtrip():
    spec = lutinfer.LutSpec("f", (
        lutinfer.ArgSpec("x", "int8", 8),
        lutinfer.ArgSpec("b", "bit", 1),
        lutinfer.ArgSpec("v", "arr_bit", 4, 4),
    ))
    assert spec.total_bits == 13 and spec.domain == 8192
    import jax.numpy as jnp
    for idx in (0, 1, 777, 8191):
        vals = lutinfer.decode_index(spec, idx)
        back = int(lutinfer.encode_args(spec, vals))
        assert back == idx, (idx, vals, back)
