"""The receiver as a program OF the framework: examples/wifi_rx.zir.

The reference's flagship is the RX chain written in the language
(SURVEY.md §2.3, §3.4) — packet detect ; LTS timing ; CFO ; channel
estimate ; SIGNAL parse ; header-driven rate dispatch via bind+branch.
These tests compile the surface program through the same parser → elab
path as every other .zir, run it on the interpreter backend over an
*impaired* quantized sample stream, and require the emitted PSDU bits
to equal phy/wifi/rx.receive()'s output bit-for-bit, plus a full CLI
file-I/O pass (the reference's golden-file discipline).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ziria_tpu.frontend import compile_file
from ziria_tpu.interp.interp import run
from ziria_tpu.phy import channel
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.runtime.buffers import StreamSpec, read_stream, write_stream
from ziria_tpu.runtime.cli import main as cli_main
from ziria_tpu.utils.bits import bytes_to_bits

SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                   "wifi_rx.zir")


def _impaired_capture(mbps: int, n_bytes: int, seed: int,
                      cfo: float = 0.002):
    """TX frame (FCS appended — the in-language receiver validates
    and strips it) + CFO/AWGN, quantized to the complex16 wire format
    (int16 pairs) both receivers consume identically — the shared
    recipe in phy/channel.py (also used by the wifi_rx golden)."""
    return channel.impaired_capture(mbps, n_bytes, seed, cfo=cfo,
                                    add_fcs=True)


@pytest.mark.parametrize("mbps,n_bytes", [(6, 30), (9, 33), (12, 40),
                                          (18, 45), (24, 60), (36, 70),
                                          (48, 81), (54, 90)])
def test_wifi_rx_zir_matches_receive(mbps, n_bytes):
    psdu, xi = _impaired_capture(mbps, n_bytes, seed=mbps)
    res = rx.receive(xi.astype(np.float32), check_fcs=True)
    # the library receiver sees the whole PSDU incl. the 4 FCS bytes
    # and validates it; the in-language receiver strips the FCS
    assert res.ok and res.rate_mbps == mbps
    assert res.length_bytes == n_bytes + 4 and res.crc_ok
    want = np.asarray(bytes_to_bits(psdu))
    np.testing.assert_array_equal(res.psdu_bits[: 8 * n_bytes], want)

    prog = compile_file(SRC)
    out = run(prog.comp, [p for p in xi]).out_array()
    np.testing.assert_array_equal(np.asarray(out, np.uint8), want)


def test_wifi_rx_zir_cli_golden(tmp_path):
    """Full driver pass: complex16 bin file in, bit file out."""
    mbps, n_bytes = 24, 50
    psdu, xi = _impaired_capture(mbps, n_bytes, seed=7)
    res = rx.receive(xi.astype(np.float32))
    assert res.ok

    inf = tmp_path / "rx_in.bin"
    outf = tmp_path / "rx_out.bin"
    write_stream(StreamSpec(ty="complex16", path=str(inf), mode="bin"), xi)
    rc = cli_main([
        f"--src={SRC}",
        "--input=file", f"--input-file-name={inf}",
        "--input-file-mode=bin",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=bin", "--backend=interp",
    ])
    assert rc == 0
    got = read_stream(StreamSpec(ty="bit", path=str(outf), mode="bin"))
    # bin bit streams pad to a byte boundary (8 * 50 bytes is aligned)
    np.testing.assert_array_equal(got[: 8 * n_bytes],
                                  np.asarray(bytes_to_bits(psdu)))


def test_wifi_rx_zir_bad_header_emits_nothing():
    """Noise-only stream after a fake detection never parses a valid
    SIGNAL: the computer must terminate without emitting."""
    rng = np.random.default_rng(0)
    # strong periodic-16 tone so the detector arms, then garbage
    t = np.arange(1200)
    tone = np.stack([np.cos(2 * np.pi * t / 16) * 800,
                     np.sin(2 * np.pi * t / 16) * 800], axis=1)
    xi = (tone + rng.normal(scale=30, size=tone.shape)).astype(np.int16)
    prog = compile_file(SRC)
    out = run(prog.comp, [p for p in xi]).out_array()
    assert out.size == 0


def test_wifi_tx_full_zir_matches_encode_frame():
    """The COMPLETE transmitter as a program of the framework
    (examples/wifi_tx_full.zir): preamble + SIGNAL + DATA symbols must
    equal phy/wifi/tx.encode_frame within 1 LSB at quantization scale
    512 — the TX-side dual of the in-language receiver."""
    src = os.path.join(os.path.dirname(__file__), "..", "examples",
                       "wifi_tx_full.zir")
    rng = np.random.default_rng(21)
    psdu = rng.integers(0, 256, 100).astype(np.uint8)
    bits = np.asarray(bytes_to_bits(psdu)).astype(np.uint8)

    prog = compile_file(src)
    out = np.asarray(run(prog.comp, list(bits)).out_array())
    want = np.round(np.asarray(tx.encode_frame(psdu, 6)) * 512.0)
    assert out.shape == want.shape
    assert np.abs(out - want).max() <= 1.0

    # and the in-language RECEIVER decodes the in-language TRANSMITTER:
    # the full PHY loop entirely as programs of the framework
    res = rx.receive(out.astype(np.float32) / 512.0, max_samples=4096)
    assert res.ok and res.rate_mbps == 6 and res.length_bytes == 100
    np.testing.assert_array_equal(res.psdu_bits, bits)


def test_wifi_rx_zir_continuous_two_frames():
    # the reference receiver runs FOREVER (repeat around the frame
    # computer); wrapping rx() in `repeat` must decode back-to-back
    # frames from one stream — packet detect re-arms on the second
    # frame's STS through inter-frame noise, and the chunked state
    # machines' window over-pull must hand the second frame's samples
    # back intact (interp.Source pushback across frames)
    import re

    from ziria_tpu.backend import hybrid as H
    from ziria_tpu.frontend import compile_source
    from ziria_tpu.utils.bits import bytes_to_bits

    src_txt = open(SRC).read()
    src_txt = re.sub(
        r"let comp main = read\[complex16\] >>> rx\(\) >>> write\[bit\]",
        "let comp main = read[complex16] >>> repeat { rx() } "
        ">>> write[bit]", src_txt)
    prog = compile_source(src_txt, src_name=SRC,
                          base_dir=os.path.dirname(SRC))

    psdu1, x1 = _impaired_capture(24, 60, seed=31)
    psdu2, x2 = _impaired_capture(54, 90, seed=32)
    xs = list(np.concatenate([np.asarray(x1), np.asarray(x2)], axis=0))
    want = np.concatenate([np.asarray(bytes_to_bits(psdu1)),
                           np.asarray(bytes_to_bits(psdu2))])

    got_i = run(prog.comp, xs).out_array()
    np.testing.assert_array_equal(np.asarray(got_i, np.uint8), want)
    got_h = run(H.hybridize(prog.comp), xs).out_array()
    np.testing.assert_array_equal(np.asarray(got_h, np.uint8), want)


def test_wifi_rx_zir_fcs_rejects_corruption():
    """VERDICT r3 next #8: the in-language CRC block (reference RX ends
    `... descramble >>> crc`, SURVEY.md §3.4) drops corrupted frames —
    and frames without an FCS — entirely in-language."""
    from ziria_tpu.backend import hybrid as H

    psdu, xi = _impaired_capture(24, 60, seed=77)
    hyb = H.hybridize(compile_file(SRC).comp)
    ok = run(hyb, [p for p in xi]).out_array()
    np.testing.assert_array_equal(np.asarray(ok, np.uint8),
                                  np.asarray(bytes_to_bits(psdu)))

    # corrupt data-region samples: header still parses, payload CRC
    # fails, the frame must emit NOTHING (both backends)
    xc = np.array(xi)
    xc[400:420] = -xc[400:420]
    assert run(hyb, [p for p in xc]).out_array().size == 0
    assert run(compile_file(SRC).comp,
               [p for p in xc]).out_array().size == 0

    # a frame whose TX never appended an FCS is likewise rejected
    _p2, x2 = channel.impaired_capture(24, 60, seed=78, add_fcs=False)
    assert run(hyb, [p for p in x2]).out_array().size == 0


def test_wifi_rx_zir_continuous_drops_bad_frame():
    """Resilience: in a back-to-back stream, a frame corrupted in its
    DATA region is dropped by the in-language FCS while the frames
    around it still decode — the receive loop survives a bad frame
    instead of emitting garbage into the stream."""
    import re

    from ziria_tpu.backend import hybrid as H
    from ziria_tpu.frontend import compile_source
    from ziria_tpu.utils.bits import bytes_to_bits

    src_txt = open(SRC).read()
    src_txt = re.sub(
        r"let comp main = read\[complex16\] >>> rx\(\) >>> write\[bit\]",
        "let comp main = read[complex16] >>> repeat { rx() } "
        ">>> write[bit]", src_txt)
    prog = compile_source(src_txt, src_name=SRC,
                          base_dir=os.path.dirname(SRC))

    psdu1, x1 = _impaired_capture(24, 60, seed=41)
    psdu2, x2 = _impaired_capture(36, 70, seed=42)
    psdu3, x3 = _impaired_capture(54, 90, seed=43)
    x2 = np.array(x2)
    # corrupt frame 2's DATA region (pre=60 noise + 320 preamble +
    # 80 SIGNAL = DATA from sample 460; the header must stay intact so
    # the receiver consumes exactly this frame's span)
    x2[520:536] = -x2[520:536]
    xs = list(np.concatenate([np.asarray(x1), x2, np.asarray(x3)],
                             axis=0))
    want = np.concatenate([np.asarray(bytes_to_bits(psdu1)),
                           np.asarray(bytes_to_bits(psdu3))])

    got_h = run(H.hybridize(prog.comp), xs).out_array()
    np.testing.assert_array_equal(np.asarray(got_h, np.uint8), want)
