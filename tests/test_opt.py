"""Fold/fusion pass: structural assertions + the metamorphic invariant
(folded program output == unfolded, on interpreter AND jit backend —
the reference's flag-independence test pattern, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.core.opt import fold, fold_with_stats
from ziria_tpu.interp.interp import run
from ziria_tpu.utils.diff import assert_stream_eq


def check_equiv(prog, xs, atol=0.0):
    folded = fold(prog)
    want = run(prog, list(xs)).out_array()
    got_i = run(folded, list(xs)).out_array()
    assert_stream_eq(np.asarray(got_i), want, atol=atol, name="fold/interp")
    for p, tag in ((prog, "raw/jit"), (folded, "fold/jit")):
        got = run_jit(p, np.asarray(xs), width=3)
        assert_stream_eq(np.asarray(got), want, atol=atol, name=tag)
    return folded


def test_map_map_fuses_to_one_stage():
    prog = z.pipe(z.zmap(lambda x: x + 1, name="inc"),
                  z.zmap(lambda x: x * 3, name="tri"))
    folded = check_equiv(prog, np.arange(24, dtype=np.int32))
    assert isinstance(folded, ir.Map)  # single fused stage


def test_repeat_take_emit_becomes_map():
    prog = z.repeat(z.let("x", z.take, z.emit1(lambda e: e["x"] * 2)))
    folded = check_equiv(prog, np.arange(12, dtype=np.int32))
    assert isinstance(folded, ir.Map)
    assert folded.in_arity == 1 and folded.out_arity == 1


def test_repeat_takes_emits_becomes_map():
    prog = z.repeat(z.let("v", z.takes(2),
                          z.emits(lambda e: e["v"][::-1], 2)))
    folded = check_equiv(prog, np.arange(20, dtype=np.int32))
    assert isinstance(folded, ir.Map)
    assert folded.in_arity == 2 and folded.out_arity == 2


def test_repeat_take_emit_then_map_fuses_fully():
    # fold turns the repeat into a Map, then map-map fusion collapses the
    # whole pipeline into ONE stage
    prog = z.pipe(
        z.repeat(z.let("x", z.take, z.emit1(lambda e: e["x"] + 10))),
        z.zmap(lambda x: x * 2, name="dbl"),
        z.zmap(lambda x: x - 1, name="dec"))
    folded = check_equiv(prog, np.arange(16, dtype=np.int32))
    assert isinstance(folded, ir.Map)


def test_map_accum_fusion():
    def acc(s, x):
        return s + x, s + x

    prog = z.pipe(z.zmap(lambda x: x * 2, name="dbl"),
                  z.map_accum(acc, 0, name="cumsum"),
                  z.zmap(lambda x: x + 1, name="inc"))
    folded = check_equiv(prog, np.arange(18, dtype=np.int32))
    assert isinstance(folded, ir.MapAccum)  # one fused stateful stage


def test_scoped_repeat_not_rewritten():
    # the emit closure reads an outer ref -> R3 must NOT fire
    prog = z.let_ref(
        "g", 100,
        z.repeat(z.let("x", z.take,
                       z.emit1(lambda e: e["x"] + e["g"]))))
    folded = fold(prog)
    assert isinstance(folded, ir.LetRef)
    assert isinstance(folded.body, ir.Repeat)  # untouched
    want = run(prog, list(range(6))).out_array()
    got = run(folded, list(range(6))).out_array()
    assert_stream_eq(np.asarray(got), np.asarray(want))


def test_const_branch_selected():
    # a raw Branch is interpreter-only; folding selects the arm and
    # thereby ENABLES jit lowering
    prog = z.branch(True, z.zmap(lambda x: x + 1),
                    z.zmap(lambda x: x - 1))
    folded = fold(prog)
    assert isinstance(folded, ir.Map)
    xs = np.arange(10, dtype=np.int32)
    want = run(prog, list(xs)).out_array()
    got = run_jit(folded, xs, width=2)
    assert_stream_eq(np.asarray(got), np.asarray(want))


def test_fixpoint_terminates_and_counts():
    stages = [z.zmap(lambda x, _k=k: x + _k) for k in range(6)]
    prog = z.pipe(*stages)
    folded, stats = fold_with_stats(prog)
    assert isinstance(folded, ir.Map)
    assert stats.rewrites >= 5


def test_run_jit_optimize_flag():
    prog = z.pipe(
        z.repeat(z.let("x", z.take, z.emit1(lambda e: e["x"] + 5))),
        z.zmap(lambda x: x * 2))
    xs = np.arange(21, dtype=np.int32)
    want = run(prog, list(xs)).out_array()
    got = run_jit(prog, xs, width=2, optimize=True)
    assert_stream_eq(np.asarray(got), np.asarray(want))


def test_wifi_tx_pipeline_folds_and_matches():
    # the real TX symbol pipeline still produces identical output
    from ziria_tpu.phy.wifi import tx
    prog = tx.tx_symbol_pipeline(24)
    folded, stats = fold_with_stats(prog)
    rate_bits = np.random.default_rng(0).integers(
        0, 2, 5 * 96).astype(np.uint8)
    want = run(prog, list(rate_bits)).out_array()
    got = run(folded, list(rate_bits)).out_array()
    assert_stream_eq(np.asarray(got), np.asarray(want), atol=1e-6)
