"""Static expression typechecker tests (VERDICT round 1, next-round #3).

The corpus of bad programs mirrors the reference's TcExpr/TcUnify
coverage (SURVEY.md §2.1): dtype mismatches, array-length arithmetic,
ext-signature enforcement, struct field checking — all rejected at
compile time with a located (line:col) error, and a set of positive
programs asserting the checker changes nothing for well-typed code.
"""

import re

import numpy as np
import pytest

from ziria_tpu.frontend import ZiriaTypeError, compile_source


def bad(src: str, match: str) -> None:
    with pytest.raises(ZiriaTypeError) as ei:
        compile_source(src)
    msg = str(ei.value)
    assert re.search(match, msg), f"wanted /{match}/ in: {msg}"
    # located: <src>:line:col: present
    assert re.search(r":\d+:\d+:", msg), f"no line:col in: {msg}"


PIPE = "let comp main = read[int32] >>> map f >>> write[int32]"


# ------------------------------------------------------------------
# 1-5: array lengths
# ------------------------------------------------------------------


def test_bad_array_init_length():
    bad("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32 := {1, 2, 3, 4, 5};
        return a[0]
      }
    """ + PIPE, "length mismatch")


def test_bad_slice_beyond_end():
    bad("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32;
        var b : arr[2] int32;
        b := a[3, 2];
        return b[0]
      }
    """ + PIPE, "out of bounds")


def test_bad_static_index():
    bad("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32;
        return a[4]
      }
    """ + PIPE, "out of bounds")


def test_bad_array_assign_length():
    bad("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32;
        var b : arr[8] int32;
        a := b;
        return a[0]
      }
    """ + PIPE, "length mismatch")


def test_bad_binop_array_lengths():
    bad("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32;
        var b : arr[8] int32;
        var c : arr[4] int32;
        c := a + b;
        return c[0]
      }
    """ + PIPE, "different lengths")


# ------------------------------------------------------------------
# 6-9: dtype discipline
# ------------------------------------------------------------------


def test_bad_complex_to_int():
    bad("""
      fun f(x: int32) : int32 {
        var z : complex16;
        var n : int32;
        n := z;
        return n
      }
    """ + PIPE, "explicit cast")


def test_bad_double_to_int():
    bad("""
      fun f(x: int32) : int32 {
        var d : double := 1.5;
        var n : int32;
        n := d;
        return n
      }
    """ + PIPE, "explicit cast")


def test_bad_shift_on_complex():
    bad("""
      fun f(x: int32) : int32 {
        var z : complex16;
        z := z << 2;
        return x
      }
    """ + PIPE, "shift")


def test_bad_ordering_on_complex():
    bad("""
      fun f(x: int32) : int32 {
        var z : complex16;
        if z < z then { return 1 }
        return 0
      }
    """ + PIPE, "complex")


# ------------------------------------------------------------------
# 10-12: function/ext signatures
# ------------------------------------------------------------------


def test_bad_ext_arity():
    bad("""
      ext fun sqrt(x: double) : double
      fun f(x: int32) : int32 {
        var d : double := sqrt(1.0, 2.0);
        return x
      }
    """ + PIPE, "expected 1 argument")


def test_bad_ext_arg_length():
    bad("""
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      fun g() : complex16 {
        var a : arr[32] complex16;
        var b : arr[64] complex16;
        b := v_fft(a);
        return b[0]
      }
      fun f(x: int32) : int32 { var z : complex16 := g(); return x }
    """ + PIPE, "expects arr\\[64\\]")


def test_bad_fun_arg_scalar_for_array():
    bad("""
      fun g(a: arr[4] int32) : int32 { return a[0] }
      fun f(x: int32) : int32 { return g(x) }
    """ + PIPE, "expects arr\\[4\\]")


# ------------------------------------------------------------------
# 13-15: structs, fields, return types
# ------------------------------------------------------------------


def test_bad_struct_field():
    bad("""
      struct P = { re: int32; im: int32 }
      fun f(x: int32) : int32 {
        var p : P;
        return p.zz
      }
    """ + PIPE, "no field")


def test_bad_struct_literal_missing_field():
    bad("""
      struct P = { a: int32; b: int32 }
      fun f(x: int32) : int32 {
        var p : P := P { a = 1 };
        return p.a
      }
    """ + PIPE, "missing field")


def test_bad_return_type():
    bad("""
      fun f(x: int32) : int32 {
        var z : complex16;
        return z
      }
    """ + PIPE, "declared")


# ------------------------------------------------------------------
# 16-20: more — assignment discipline, unbound, emits, conditions
# ------------------------------------------------------------------


def test_bad_assign_to_immutable_let():
    bad("""
      fun f(x: int32) : int32 {
        let k = 3;
        k := 4;
        return k
      }
    """ + PIPE, "immutable")


def test_bad_assign_to_bind_var():
    bad("""
      fun f(x: int32) : int32 { return x }
      let comp main = read[int32] >>>
        repeat { y <- take; do { y := 3 }; emit y } >>> write[int32]
    """, "immutable|unbound")


def test_bad_unbound_in_fun_body():
    bad("""
      fun f(x: int32) : int32 { return nosuchvar }
    """ + PIPE, "unbound")


def test_bad_emits_scalar():
    bad("""
      let comp main = read[int32] >>>
        repeat { x <- take; var s : int32 := 0; emits s }
        >>> write[int32]
    """, "emits")


def test_bad_scalar_to_array_var():
    bad("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32;
        a := x;
        return a[0]
      }
    """ + PIPE, "explicit cast|array")


# ------------------------------------------------------------------
# 21-23: comp level — annotated binds, comp fun args, takes length
# ------------------------------------------------------------------


def test_bad_annotated_bind_length():
    bad("""
      let comp main = read[int32] >>>
        repeat { (x : arr[8] int32) <- takes 4; emits x }
        >>> write[int32]
    """, "length mismatch|expected 8")


def test_bad_comp_fun_array_arg():
    bad("""
      fun comp g(h: arr[64] complex16) { x <- take; emit x }
      let comp main = read[complex16] >>>
        { var e : arr[32] complex16; g(e) } >>> write[complex16]
    """, "expects arr\\[64\\]")


def test_bad_cast_of_struct():
    bad("""
      struct P = { a: int32; b: int32 }
      fun f(x: int32) : int32 {
        var p : P;
        return int32(p)
      }
    """ + PIPE, "cast")


# ------------------------------------------------------------------
# positives: the checker must not reject well-typed idioms
# ------------------------------------------------------------------


GOOD = [
    # static scalars adapt to any numeric slot (weak literals)
    """
    fun f(x: int32) : int32 {
      var d : double := 0;
      var a : arr[3] double := {1, 2, 3};
      d := 1;
      return x
    }
    """ + PIPE,
    # int widths mix freely (C wrap policy), int widens to double/complex
    """
    fun f(x: int32) : int32 {
      var a : int8 := 100;
      var b : int32 := 1000;
      var d : double := 0.0;
      var z : complex16;
      a := b; b := a; d := b; z := complex16(b, b);
      return b
    }
    """ + PIPE,
    # length-polymorphic params adopt argument lengths
    """
    fun total(a: arr int32) : int32 {
      var s : int32 := 0;
      for i in [0, length(a)] { s := s + a[i] }
      return s
    }
    fun f(x: int32) : int32 {
      var a : arr[5] int32;
      a[0] := x;
      return total(a)
    }
    """ + PIPE,
    # slices: static offset+length inside bounds; elem ops elementwise
    """
    fun f(x: int32) : int32 {
      var a : arr[8] int32;
      var b : arr[4] int32;
      b := a[2, 4];
      a[0, 4] := b + b;
      return b[0]
    }
    """ + PIPE,
    # .re/.im on complex; abs() of complex is double
    """
    fun f(x: int32) : int32 {
      var z : complex16 := complex16(3, 4);
      var d : double := z.re * z.re + abs(z);
      return x
    }
    """ + PIPE,
    # annotated bind with matching takes length
    """
    let comp main = read[int32] >>>
      repeat { (x : arr[4] int32) <- takes 4; emits x }
      >>> write[int32]
    """,
]


@pytest.mark.parametrize("src", GOOD, ids=range(len(GOOD)))
def test_well_typed_programs_pass(src):
    compile_source(src)


def test_typecheck_can_be_disabled():
    # the bad program from test_bad_static_index compiles with
    # typecheck=False (escape hatch, used by nothing in-tree)
    compile_source("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32;
        return a[0]
      }
    """ + PIPE, typecheck=False)


def test_error_is_elab_error_subclass():
    from ziria_tpu.frontend import ElabError
    assert issubclass(ZiriaTypeError, ElabError)


def test_well_typed_execution_unchanged():
    """A checked program still runs identically on the interpreter."""
    from ziria_tpu.interp.interp import run
    prog = compile_source("""
      fun f(x: int32) : int32 { return x * 2 + 1 }
    """ + PIPE)
    res = run(prog.comp, list(np.arange(4, dtype=np.int32)))
    np.testing.assert_array_equal(res.out_array(), [1, 3, 5, 7])
