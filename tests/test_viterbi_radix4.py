"""Radix-4 ACS, int8+LUT metrics, and the fused demap front end
(ISSUE 6; docs/quantized_viterbi.md, docs/architecture.md decode
roofline).

Contract layers pinned here:

1. radix-4 == radix-2 BIT-IDENTITY at float32 and int16 — by
   construction (the pair bodies in ops/viterbi_pallas derive it), so
   the pins run on noisy inputs where luck cannot mask a divergence:
   the plain batch decode, the windowed decode, and the all-8-rates
   mixed-rate receive surface.
2. int8+LUT: the kernel agrees with the int8 lax.scan reference and
   with the f32 oracle on the SAME quantized inputs (these seeds), and
   on raw noisy inputs its error RATE stays inside a bounded envelope
   of the f32 decode — the statistical contract
   (tests/test_windowed_ber_guard.py's form; 4-bit quantization
   legitimately moves near-tie decisions, so the margins are wider
   than the int16 guard's).
3. fused demap front end == the XLA demap/deinterleave/depuncture
   front end, bit for bit, at both a 1-symbol-per-block rate (54) and
   a multi-symbol-per-block rate (6), through decode_data_batch and
   per-capture receive().
4. knob plumbing: validation, env defaults, CLI mirror, and the
   cache-key discipline (resolved radix, never None-meaning-env).

Kernel tests run in Pallas interpret mode on CPU (conftest pins the
backend); heavy studies are tier-2 `slow`.
"""

import importlib.util
import os

import jax
import numpy as np
import pytest

from ziria_tpu.ops import viterbi, viterbi_pallas

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "windowed_ber", os.path.join(_REPO, "tools", "windowed_ber.py"))
_wb = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_wb)
_frames = _wb.make_coded_frames     # ONE signal recipe with the study


@pytest.fixture(scope="module")
def corpus():
    """One small noisy corpus + the radix-2 decodes of it, shared by
    every parity test so each (metric, radix) kernel compiles ONCE at
    one geometry (tier-1 budget)."""
    rng = np.random.default_rng(46)
    msgs, llrs = _frames(rng, 8, 256, amp=1.2)
    base_f32 = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs))
    base_i16 = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int16"))
    return msgs, llrs, base_f32, base_i16


def test_radix4_f32_bit_identical(corpus):
    msgs, llrs, base_f32, _i16 = corpus
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(llrs, radix=4))
    np.testing.assert_array_equal(got, base_f32)
    # and the corpus exercises an OPERATING decoder, not a trivial one
    assert 0 < (base_f32 != msgs).mean() < 0.15


def test_radix4_int16_bit_identical(corpus):
    _msgs, llrs, _f32, base_i16 = corpus
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int16", radix=4))
    np.testing.assert_array_equal(got, base_i16)


def test_radix4_windowed_bit_identical(corpus):
    # the radix knob reaches the windowed decode's Pallas engine: the
    # windows of a longer frame decode identically under either radix.
    # window=64 makes the window extent 64+2*96 = 256 — the SAME tile
    # geometry as the corpus fixture, so no fresh interpret-mode
    # kernel trace is paid (tier-1 budget)
    rng = np.random.default_rng(47)
    _msgs, llrs = _frames(rng, 2, 512, amp=1.2)
    w2 = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=64, radix=2))
    w4 = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=64, radix=4))
    np.testing.assert_array_equal(w4, w2)


# ------------------------------------------------------------------ int8


def test_int8_kernel_matches_scan_and_f32_on_same_q(corpus):
    # on the SAME quantized inputs the int8 kernel, the int8 scan
    # reference, and the f32 decode agree bit for bit at these seeds:
    # the saturation rail never touches a surviving path here, and
    # integer branch metrics are exact in every arithmetic. (This is
    # an empirical pin at fixed seeds, not the int16 path's proof —
    # the int8 CONTRACT is the BER envelope below.)
    _msgs, llrs, _f32, _i16 = corpus
    q, _scale = viterbi.quantize_llrs(llrs,
                                      qmax=viterbi.INT8_QUANT_MAX)
    kern2 = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int8"))
    kern4 = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int8", radix=4))
    np.testing.assert_array_equal(kern4, kern2)   # r4 == r2 exactly
    scan8 = np.asarray(jax.vmap(viterbi.viterbi_decode_int8)(
        np.asarray(q, np.int32)))
    np.testing.assert_array_equal(kern2, scan8)
    f32_on_q = np.asarray(jax.vmap(viterbi.viterbi_decode)(
        np.asarray(q, np.float32)))
    np.testing.assert_array_equal(kern2, f32_on_q)


def _scan_i8(x):
    """The int8 decode's scan engine (quantize at the int8 level +
    int8 scan reference) — the BER study's cheap engine, mirroring
    test_viterbi_int16._scan_i16."""
    q, _ = viterbi.quantize_llrs(x, qmax=viterbi.INT8_QUANT_MAX)
    return np.asarray(jax.vmap(viterbi.viterbi_decode_int8)(
        np.asarray(q, np.int32)))


def test_int8_ber_guard():
    # raw noisy floats at the operating point and below the waterfall:
    # 4-bit soft quantization may move individual decisions, but the
    # error RATE must stay inside a bounded envelope of the f32
    # decode. Margins are wider than the int16 guard's (that path
    # quantizes at 127 levels, this one at 15): measured deltas at
    # these seeds are ~3e-3 at amp 1.2 and ~6e-3 (2% rel) at 0.9.
    for seed, amp in ((3, 1.2), (7, 0.9)):
        rng = np.random.default_rng(seed)
        msgs, llrs = _frames(rng, 4, 2048, amp=amp)
        f32 = np.asarray(jax.vmap(viterbi.viterbi_decode)(llrs))
        i8 = _scan_i8(llrs)
        ber_f = (f32 != msgs).mean()
        ber_q = (i8 != msgs).mean()
        assert abs(ber_q - ber_f) < 0.05 * max(ber_f, 1e-9) + 4e-3, \
            (amp, ber_f, ber_q)


def test_int8_quantize_level():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(3, 64, 2)).astype(np.float32) * 5.0
    q, scale = viterbi.quantize_llrs(x, qmax=viterbi.INT8_QUANT_MAX)
    q = np.asarray(q)
    assert q.dtype == np.int16          # proven tile dtype carries it
    np.testing.assert_array_equal(
        np.abs(q).max(axis=(1, 2)), [viterbi.INT8_QUANT_MAX] * 3)
    # the int8 rail must clear the per-step drift by a sane margin
    assert 2 * viterbi.INT8_QUANT_MAX < -viterbi.I8_MIN


# ------------------------------------------------------- fused front end


def _fused_vs_unfused(mbps, n_bytes, seed):
    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols
    from ziria_tpu.utils.bits import bytes_to_bits

    rng = np.random.default_rng(seed)
    rate = RATES[mbps]
    n_sym = n_symbols(n_bytes, rate)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, mbps))
    frames = (np.broadcast_to(frame, (3,) + frame.shape)
              + rng.normal(0, 0.03, (3,) + frame.shape)
              ).astype(np.float32)
    want = np.asarray(bytes_to_bits(psdu))
    base, svc = [np.asarray(a) for a in rx.decode_data_batch(
        frames, rate, n_sym, 8 * n_bytes)]
    fused, svc_f = [np.asarray(a) for a in rx.decode_data_batch(
        frames, rate, n_sym, 8 * n_bytes, fused_demap=True)]
    np.testing.assert_array_equal(base[0], want)   # operating decode
    np.testing.assert_array_equal(fused, base)
    np.testing.assert_array_equal(svc_f, svc)
    # radix-4 stacks on the fused prologue
    fused4 = np.asarray(rx.decode_data_batch(
        frames, rate, n_sym, 8 * n_bytes, fused_demap=True,
        viterbi_radix=4)[0])
    np.testing.assert_array_equal(fused4, base)


def test_fused_demap_bit_identical_rate6():
    # rate 6 = the multi-symbol-per-block path (spb=3, and n_sym pads
    # 5 -> 6) AND the cheapest fused kernel program (72-step blocks) —
    # the tier-1 fused pin; the 54 Mbps 1-symbol-per-block twin runs
    # tier-2 below (its 216-step interpret-mode program is minutes on
    # CPU, milliseconds-to-compile on the chip)
    _fused_vs_unfused(6, 10, seed=54)


@pytest.mark.slow
def test_fused_demap_bit_identical_rate54():
    _fused_vs_unfused(54, 100, seed=102)


@pytest.mark.slow
def test_receive_fused_demap_and_radix_identity():
    # the per-capture receiver's bucketed decode (traced n_bits_real
    # mask) under the fused prologue and the radix knob — rate 6
    # shares the fused kernel programs the batch test above compiled.
    # Tier-2: the bucketed geometry (n_sym_p = 9) is a fresh ~90 s
    # interpret-mode trace on CPU, and the fused-front contract is
    # already pinned tier-1 through decode_data_batch
    from ziria_tpu.phy.wifi import rx, tx

    rng = np.random.default_rng(50)
    psdu = rng.integers(0, 256, 10).astype(np.uint8)
    cap = np.concatenate([np.zeros((50, 2), np.float32),
                          np.asarray(tx.encode_frame(psdu, 6))])
    r0 = rx.receive(cap, check_fcs=False)
    r1 = rx.receive(cap, fused_demap=True)
    r2 = rx.receive(cap, fused_demap=True, viterbi_radix=4)
    assert r0.ok and r1.ok and r2.ok
    np.testing.assert_array_equal(r1.psdu_bits, r0.psdu_bits)
    np.testing.assert_array_equal(r2.psdu_bits, r0.psdu_bits)


def test_fused_demap_falls_back_under_window_and_quantized():
    # composition rule: windowed / quantized decodes keep the unfused
    # front (the fused prologue cannot express LLR windows or the
    # whole-frame quantization scale) — results equal the plain modes
    from ziria_tpu.phy.wifi import rx
    assert rx._fused_front_applies(None, None)
    assert rx._fused_front_applies(0, "float32")
    assert not rx._fused_front_applies(512, None)
    assert not rx._fused_front_applies(None, "int16")
    assert not rx._fused_front_applies(None, "int8")


# ------------------------------------------------- mixed-rate surfaces


N_BYTES = 16   # the suite-shared mixed-dispatch geometry
               # (tests/test_rx_mixed_dispatch.py): 8-symbol common
               # bucket, one compiled mixed decode per radix


@pytest.mark.slow
def test_receive_many_all_8_rates_radix4_bit_identical():
    # the acceptance pin: radix-4 through the REAL mixed-rate receive
    # surface, lane for lane across all 8 rates, against the radix-2
    # oracle (tier-2: one fresh T=1728 interpret-mode mixed compile)
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy.wifi import tx
    from ziria_tpu.phy.wifi.params import RATES

    rng = np.random.default_rng(20260803)
    caps = []
    for m in sorted(RATES):
        psdu = rng.integers(0, 256, N_BYTES).astype(np.uint8)
        s = np.asarray(tx.encode_frame(psdu, m))
        caps.append(np.concatenate(
            [np.zeros((50, 2), np.float32), s], axis=0))
    r2 = framebatch.receive_many(caps, viterbi_radix=2)
    r4 = framebatch.receive_many(caps, viterbi_radix=4)
    assert [a.rate_mbps for a in r4] == sorted(RATES)
    for a, b in zip(r2, r4):
        assert a.ok and b.ok and a.rate_mbps == b.rate_mbps
        np.testing.assert_array_equal(b.psdu_bits, a.psdu_bits)


@pytest.mark.slow
def test_decode_data_mixed_radix4_int16_bit_identical():
    # the same pin one layer down at int16 metrics, without paying a
    # second acquisition pass: decode the mixed batch directly
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy.wifi import tx
    from ziria_tpu.phy.wifi.params import RATES

    rng = np.random.default_rng(20260804)
    caps = []
    for m in sorted(RATES):
        psdu = rng.integers(0, 256, N_BYTES).astype(np.uint8)
        s = np.asarray(tx.encode_frame(psdu, m))
        caps.append(np.concatenate(
            [np.zeros((50, 2), np.float32), s], axis=0))
    r2 = framebatch.receive_many(caps, viterbi_metric="int16",
                                 viterbi_radix=2)
    r4 = framebatch.receive_many(caps, viterbi_metric="int16",
                                 viterbi_radix=4)
    for a, b in zip(r2, r4):
        assert a.ok and b.ok
        np.testing.assert_array_equal(b.psdu_bits, a.psdu_bits)


# ------------------------------------------------------------ knobs


def test_radix_validation_and_env_default(monkeypatch):
    monkeypatch.delenv("ZIRIA_VITERBI_RADIX", raising=False)
    assert viterbi._check_radix(None) == 2
    assert viterbi._check_radix(4) == 4
    with pytest.raises(ValueError, match="radix"):
        viterbi._check_radix(3)
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "4")
    assert viterbi._check_radix(None) == 4
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "8")
    with pytest.raises(ValueError, match="ZIRIA_VITERBI_RADIX"):
        viterbi._check_radix(None)
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "two")
    with pytest.raises(ValueError, match="ZIRIA_VITERBI_RADIX"):
        viterbi._check_radix(None)
    # explicit argument wins over the env
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "4")
    assert viterbi._check_radix(2) == 2


def test_fused_demap_env_default(monkeypatch):
    from ziria_tpu.phy.wifi import rx
    monkeypatch.delenv("ZIRIA_FUSED_DEMAP", raising=False)
    assert rx.fused_demap_enabled(None) is False    # default OFF
    monkeypatch.setenv("ZIRIA_FUSED_DEMAP", "1")
    assert rx.fused_demap_enabled(None) is True
    assert rx.fused_demap_enabled(False) is False   # arg wins
    monkeypatch.setenv("ZIRIA_FUSED_DEMAP", "0")
    assert rx.fused_demap_enabled(None) is False


def test_cli_choices_mirror_radixes():
    # runtime/cli.py hardcodes --viterbi-radix choices so --help stays
    # import-light; pin them to the ops-layer registry (the
    # --viterbi-metric mirror rule, test_viterbi_int16)
    from ziria_tpu.runtime.cli import build_parser
    for a in build_parser()._actions:
        if a.dest == "viterbi_radix":
            assert tuple(a.choices) == viterbi.RADIXES
            return
    raise AssertionError("--viterbi-radix flag missing")


def test_metric_dtypes_include_int8_everywhere():
    assert "int8" in viterbi.METRIC_DTYPES
    # the scan decode accepts it end to end
    rng = np.random.default_rng(2)
    _msgs, llrs = _frames(rng, 1, 96, amp=3.0)
    a = np.asarray(viterbi.viterbi_decode(llrs[0], metric_dtype="int8"))
    b = np.asarray(viterbi.viterbi_decode(llrs[0]))
    np.testing.assert_array_equal(a, b)   # clean input: same decode


def test_env_radix_reaches_staged_viterbi_mode(monkeypatch):
    from ziria_tpu.frontend import externals
    monkeypatch.delenv("ZIRIA_VITERBI_WINDOW", raising=False)
    monkeypatch.delenv("ZIRIA_VITERBI_METRIC", raising=False)
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "4")
    assert externals.viterbi_mode() == (0, "float32", 4)
    monkeypatch.setenv("ZIRIA_VITERBI_RADIX", "5")
    with pytest.raises(ValueError, match="ZIRIA_VITERBI_RADIX"):
        externals.viterbi_mode()
