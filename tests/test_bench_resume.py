"""Stage-resume logic of the bench harness (bench.py).

The axon TPU backend flaps: round 4 lost two open windows because every
child invocation re-measured already-captured stages from zero before
its 480 s budget killed it (VERDICT r4 missing #1). bench.py therefore
reuses stage records from BENCH_PARTIAL.jsonl when they are recent,
same-schema-version, and same-platform. These tests pin the eligibility
rules — reusing a stale, foreign-platform, or error record would
publish a wrong number, so the filter is load-bearing.
"""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _bench()
NOW = 1_000_000.0
VER = bench.BENCH_STAGE_VERSION


def _write(tmp_path, recs):
    p = tmp_path / "partial.jsonl"
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(p)


def rec(stage, t=NOW - 100, ver=VER, platform="tpu",
        workload_bytes=1000, **kv):
    return {"run_id": "rX", "stage": stage, "t": t, "ver": ver,
            "platform": platform, "workload_bytes": workload_bytes, **kv}


def test_eligibility_filters(tmp_path):
    path = _write(tmp_path, [
        rec("headline", batch=128, t_step_s=1e-3, tpu_sps=1.0),
        rec("pallas_mosaic", ver=VER - 1, pallas_mosaic=True),   # old schema
        rec("fxp_interior", platform="cpu", t_step_s=2e-3),      # wrong plat
        rec("framebatch", t=NOW - 99999, frames=16),             # too old
        rec("percall_fence", error="boom"),                      # error rec
        rec("correctness", workload_bytes=100),              # smoke workload
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    assert "headline" in out and "headline:128" in out
    assert "pallas_mosaic" not in out
    assert "fxp_interior" not in out
    assert "framebatch" not in out
    assert "percall_fence" not in out
    assert "correctness" not in out


def test_chained_resume_ages_on_original_capture(tmp_path):
    # a re-emitted record carries captured_t of the ORIGINAL
    # measurement; the window gates on that, not the re-emission time
    path = _write(tmp_path, [
        rec("headline", t=NOW - 10, captured_t=NOW - 99999,
            batch=128, t_step_s=1e-3),
    ])
    assert bench._load_resume("tpu", 3600, now=NOW, path=path) == {}
    # but a fresh record the same age IS eligible
    path2 = _write(tmp_path, [
        rec("headline", t=NOW - 10, batch=128, t_step_s=1e-3)])
    assert "headline" in bench._load_resume("tpu", 3600, now=NOW,
                                            path=path2)


def test_sweep_widths_keyed_independently(tmp_path):
    path = _write(tmp_path, [
        rec("batch_sweep", batch=256, t_step_s=2e-3),
        rec("batch_sweep", batch=512, t_step_s=3e-3),
        rec("batch_sweep", batch=512, t=NOW - 50, t_step_s=4e-3),
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    assert out["batch_sweep:256"]["t_step_s"] == 2e-3
    # most recent record wins per width
    assert out["batch_sweep:512"]["t_step_s"] == 4e-3
    assert "batch_sweep" not in out


def test_headline_keeps_per_width_and_latest(tmp_path):
    # a run emits headline at B=128 then re-emits at the promoted
    # width: both widths stay resumable, "headline" = the promotion
    path = _write(tmp_path, [
        rec("headline", t=NOW - 200, batch=128, t_step_s=1e-3),
        rec("headline", t=NOW - 100, batch=512, t_step_s=2e-3),
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    assert out["headline"]["batch"] == 512
    assert out["headline:128"]["t_step_s"] == 1e-3
    assert out["headline:512"]["t_step_s"] == 2e-3


def test_stage_payload_strips_bookkeeping():
    r = rec("fxp_interior", t_step_s=1e-3, sps=5.0,
            captured_t=NOW - 5, resumed_from="r0")
    payload = bench._stage_payload(r)
    assert payload == {"t_step_s": 1e-3, "sps": 5.0}


def test_garbage_lines_ignored(tmp_path):
    p = tmp_path / "partial.jsonl"
    with open(p, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps(rec("headline", batch=128, t_step_s=1e-3))
                + "\n")
    out = bench._load_resume("tpu", 3600, now=NOW, path=str(p))
    assert "headline" in out


def test_missing_file_is_empty(tmp_path):
    out = bench._load_resume("tpu", 3600, now=NOW,
                             path=str(tmp_path / "nope.jsonl"))
    assert out == {}


def test_windowed_headline_never_seeds_exact_width_table(tmp_path):
    # a windowed-Viterbi promotion is a different decode method: it
    # must resume under its own key, never shadowing the exact step
    # at its width — even when it is the LATEST headline record
    path = _write(tmp_path, [
        rec("headline", t=NOW - 200, batch=128, t_step_s=1e-3),
        rec("headline", t=NOW - 100, batch=128, t_step_s=2e-4,
            windowed=True, window=1024, overlap=96),
        rec("batch_sweep", batch=256, t_step_s=2e-3),
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    # the exact record survives at its width key...
    assert out["headline:128"]["t_step_s"] == 1e-3
    assert "windowed" not in out["headline:128"]
    # ...and the windowed promotion lives under its own key
    assert out["headline_windowed"]["windowed"] is True
    assert out["headline"]["t_step_s"] == 1e-3   # latest EXACT headline


def test_last_good_rejects_derived_and_prefers_stamped_time(tmp_path,
                                                            monkeypatch):
    """_last_good (the promotion source when the backend is dark) must
    never re-accept a promoted result as a fresh capture, and must date
    captures by the time stamped INSIDE the JSON — file mtimes reset on
    every rewrite (r5 review: mtime laundering)."""
    live = tmp_path / "BENCH_LIVE.json"
    monkeypatch.setattr(bench, "LIVE_PATH", str(live))

    # a derived result (value_source present) is refused
    live.write_text(json.dumps(
        {"platform": "tpu", "value": 1.0, "value_source": "promoted"}))
    assert bench._last_good() is None
    # a cpu result is refused
    live.write_text(json.dumps({"platform": "cpu", "value": 1.0}))
    assert bench._last_good() is None
    # a fresh capture is accepted, dated by captured_at_unix
    live.write_text(json.dumps(
        {"platform": "tpu", "value": 2.0, "captured_at_unix": 123.0}))
    lg = bench._last_good()
    assert lg["captured_unix_mtime"] == 123.0
    # legacy capture without the stamp falls back to the file mtime
    live.write_text(json.dumps({"platform": "tpu", "value": 3.0}))
    lg = bench._last_good()
    assert abs(lg["captured_unix_mtime"] - os.path.getmtime(live)) < 1


def test_pinned_baseline_reader(tmp_path, monkeypatch):
    base = tmp_path / "BASELINE.json"
    monkeypatch.setattr(bench, "BASELINE_PATH", str(base))
    assert bench._pinned_baseline() is None          # missing file
    base.write_text(json.dumps({"pinned_baseline": {"sps": 0}}))
    assert bench._pinned_baseline() is None          # zero = unset
    base.write_text(json.dumps(
        {"pinned_baseline": {"sps": 6401460.9,
                             "pinned_at": "2026-07-31"}}))
    pin = bench._pinned_baseline()
    assert pin["sps"] == 6401460.9


def test_probe_hang_cached_within_invocation(monkeypatch):
    """A probe TIMEOUT is definitive for the invocation (the tunnel is
    down, not flaking): no same-call retry, and a second _probe call
    reuses the cached negative — BENCH_r05 paid the same 90 s hang
    2-3x per run (~200 s wall) before this memo."""
    b = _bench()                       # fresh module: isolated memo
    calls = []

    def fake_child(argv, tmo):
        calls.append(argv)
        return None, "", ""            # rc None == timeout/hang

    monkeypatch.setattr(b, "_run_one_child", fake_child)
    deadline = __import__("time").time() + 10_000
    ok, err = b._probe(deadline)
    assert not ok and "timeout" in err
    assert len(calls) == 1             # a hang is not retried
    ok2, err2 = b._probe(deadline)
    assert not ok2 and "cached" in err2
    assert len(calls) == 1             # ...and never re-paid


def test_probe_transient_rc_still_retries(monkeypatch):
    """A non-zero exit stays a transient: the retry loop (which fixed
    BENCH_r01) is untouched, and a retry that SUCCEEDS leaves no
    negative memo behind."""
    b = _bench()
    calls = []

    def fake_child(argv, tmo):
        calls.append(argv)
        return (1, "", "boom") if len(calls) == 1 else (0, "{}", "")

    monkeypatch.setattr(b, "_run_one_child", fake_child)
    monkeypatch.setattr(b, "PROBE_BACKOFF", 0)
    ok, _err = b._probe(__import__("time").time() + 10_000)
    assert ok and len(calls) == 2
    assert b._PROBE_NEG is None
