"""Stage-resume logic of the bench harness (bench.py).

The axon TPU backend flaps: round 4 lost two open windows because every
child invocation re-measured already-captured stages from zero before
its 480 s budget killed it (VERDICT r4 missing #1). bench.py therefore
reuses stage records from BENCH_PARTIAL.jsonl when they are recent,
same-schema-version, and same-platform. These tests pin the eligibility
rules — reusing a stale, foreign-platform, or error record would
publish a wrong number, so the filter is load-bearing.
"""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _bench()
NOW = 1_000_000.0
VER = bench.BENCH_STAGE_VERSION


def _write(tmp_path, recs):
    p = tmp_path / "partial.jsonl"
    with open(p, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return str(p)


def rec(stage, t=NOW - 100, ver=VER, platform="tpu",
        workload_bytes=1000, **kv):
    return {"run_id": "rX", "stage": stage, "t": t, "ver": ver,
            "platform": platform, "workload_bytes": workload_bytes, **kv}


def test_eligibility_filters(tmp_path):
    path = _write(tmp_path, [
        rec("headline", batch=128, t_step_s=1e-3, tpu_sps=1.0),
        rec("pallas_mosaic", ver=VER - 1, pallas_mosaic=True),   # old schema
        rec("fxp_interior", platform="cpu", t_step_s=2e-3),      # wrong plat
        rec("framebatch", t=NOW - 99999, frames=16),             # too old
        rec("percall_fence", error="boom"),                      # error rec
        rec("correctness", workload_bytes=100),              # smoke workload
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    assert "headline" in out and "headline:128" in out
    assert "pallas_mosaic" not in out
    assert "fxp_interior" not in out
    assert "framebatch" not in out
    assert "percall_fence" not in out
    assert "correctness" not in out


def test_chained_resume_ages_on_original_capture(tmp_path):
    # a re-emitted record carries captured_t of the ORIGINAL
    # measurement; the window gates on that, not the re-emission time
    path = _write(tmp_path, [
        rec("headline", t=NOW - 10, captured_t=NOW - 99999,
            batch=128, t_step_s=1e-3),
    ])
    assert bench._load_resume("tpu", 3600, now=NOW, path=path) == {}
    # but a fresh record the same age IS eligible
    path2 = _write(tmp_path, [
        rec("headline", t=NOW - 10, batch=128, t_step_s=1e-3)])
    assert "headline" in bench._load_resume("tpu", 3600, now=NOW,
                                            path=path2)


def test_sweep_widths_keyed_independently(tmp_path):
    path = _write(tmp_path, [
        rec("batch_sweep", batch=256, t_step_s=2e-3),
        rec("batch_sweep", batch=512, t_step_s=3e-3),
        rec("batch_sweep", batch=512, t=NOW - 50, t_step_s=4e-3),
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    assert out["batch_sweep:256"]["t_step_s"] == 2e-3
    # most recent record wins per width
    assert out["batch_sweep:512"]["t_step_s"] == 4e-3
    assert "batch_sweep" not in out


def test_headline_keeps_per_width_and_latest(tmp_path):
    # a run emits headline at B=128 then re-emits at the promoted
    # width: both widths stay resumable, "headline" = the promotion
    path = _write(tmp_path, [
        rec("headline", t=NOW - 200, batch=128, t_step_s=1e-3),
        rec("headline", t=NOW - 100, batch=512, t_step_s=2e-3),
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    assert out["headline"]["batch"] == 512
    assert out["headline:128"]["t_step_s"] == 1e-3
    assert out["headline:512"]["t_step_s"] == 2e-3


def test_stage_payload_strips_bookkeeping():
    r = rec("fxp_interior", t_step_s=1e-3, sps=5.0,
            captured_t=NOW - 5, resumed_from="r0")
    payload = bench._stage_payload(r)
    assert payload == {"t_step_s": 1e-3, "sps": 5.0}


def test_garbage_lines_ignored(tmp_path):
    p = tmp_path / "partial.jsonl"
    with open(p, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps(rec("headline", batch=128, t_step_s=1e-3))
                + "\n")
    out = bench._load_resume("tpu", 3600, now=NOW, path=str(p))
    assert "headline" in out


def test_missing_file_is_empty(tmp_path):
    out = bench._load_resume("tpu", 3600, now=NOW,
                             path=str(tmp_path / "nope.jsonl"))
    assert out == {}


def test_windowed_headline_never_seeds_exact_width_table(tmp_path):
    # a windowed-Viterbi promotion is a different decode method: it
    # must resume under its own key, never shadowing the exact step
    # at its width — even when it is the LATEST headline record
    path = _write(tmp_path, [
        rec("headline", t=NOW - 200, batch=128, t_step_s=1e-3),
        rec("headline", t=NOW - 100, batch=128, t_step_s=2e-4,
            windowed=True, window=1024, overlap=96),
        rec("batch_sweep", batch=256, t_step_s=2e-3),
    ])
    out = bench._load_resume("tpu", 3600, now=NOW, path=path)
    # the exact record survives at its width key...
    assert out["headline:128"]["t_step_s"] == 1e-3
    assert "windowed" not in out["headline:128"]
    # ...and the windowed promotion lives under its own key
    assert out["headline_windowed"]["windowed"] is True
    assert out["headline"]["t_step_s"] == 1e-3   # latest EXACT headline


def test_last_good_rejects_derived_and_prefers_stamped_time(tmp_path,
                                                            monkeypatch):
    """_last_good (the promotion source when the backend is dark) must
    never re-accept a promoted result as a fresh capture, and must date
    captures by the time stamped INSIDE the JSON — file mtimes reset on
    every rewrite (r5 review: mtime laundering)."""
    live = tmp_path / "BENCH_LIVE.json"
    monkeypatch.setattr(bench, "LIVE_PATH", str(live))

    # a derived result (value_source present) is refused
    live.write_text(json.dumps(
        {"platform": "tpu", "value": 1.0, "value_source": "promoted"}))
    assert bench._last_good() is None
    # a cpu result is refused
    live.write_text(json.dumps({"platform": "cpu", "value": 1.0}))
    assert bench._last_good() is None
    # a fresh capture is accepted, dated by captured_at_unix
    live.write_text(json.dumps(
        {"platform": "tpu", "value": 2.0, "captured_at_unix": 123.0}))
    lg = bench._last_good()
    assert lg["captured_unix_mtime"] == 123.0
    # legacy capture without the stamp falls back to the file mtime
    live.write_text(json.dumps({"platform": "tpu", "value": 3.0}))
    lg = bench._last_good()
    assert abs(lg["captured_unix_mtime"] - os.path.getmtime(live)) < 1


def test_pinned_baseline_reader(tmp_path, monkeypatch):
    base = tmp_path / "BASELINE.json"
    monkeypatch.setattr(bench, "BASELINE_PATH", str(base))
    assert bench._pinned_baseline() is None          # missing file
    base.write_text(json.dumps({"pinned_baseline": {"sps": 0}}))
    assert bench._pinned_baseline() is None          # zero = unset
    base.write_text(json.dumps(
        {"pinned_baseline": {"sps": 6401460.9,
                             "pinned_at": "2026-07-31"}}))
    pin = bench._pinned_baseline()
    assert pin["sps"] == 6401460.9


def test_probe_hang_cached_within_invocation(monkeypatch, tmp_path):
    """A probe TIMEOUT is definitive for the invocation (the tunnel is
    down, not flaking): no same-call retry, and a second _probe call
    reuses the cached negative — BENCH_r05 paid the same 90 s hang
    2-3x per run (~200 s wall) before this memo."""
    b = _bench()                       # fresh module: isolated memo
    # isolate the persistent ledger: this test is about the IN-PROCESS
    # memo, and it must neither read nor pollute the repo's ledger
    monkeypatch.setattr(b, "PROBES_PATH", str(tmp_path / "p.jsonl"))
    calls = []

    def fake_child(argv, tmo):
        calls.append(argv)
        return None, "", ""            # rc None == timeout/hang

    monkeypatch.setattr(b, "_run_one_child", fake_child)
    deadline = __import__("time").time() + 10_000
    ok, err = b._probe(deadline)
    assert not ok and "timeout" in err
    assert len(calls) == 1             # a hang is not retried
    ok2, err2 = b._probe(deadline)
    assert not ok2 and "cached" in err2
    assert len(calls) == 1             # ...and never re-paid


def _write_probes(tmp_path, recs):
    p = tmp_path / "probes.jsonl"
    with open(p, "w") as f:
        for r in recs:
            f.write(r if isinstance(r, str) else json.dumps(r))
            f.write("\n")
    return str(p)


def test_probe_ledger_fail_within_ttl_skips_probe(tmp_path, monkeypatch):
    """ISSUE 6 satellite: a ledger failure younger than the TTL is
    trusted WITHOUT re-probing — the 90 s hang is paid once per TTL
    across INVOCATIONS, not once per invocation (PR 5 only memoized
    within one)."""
    b = _bench()
    import time as _t
    now = _t.time()
    monkeypatch.setattr(b, "PROBES_PATH", _write_probes(tmp_path, [
        {"t": "garbage-iso", "probe": "fail"},          # unparseable t
        "not json at all",
        {"probe": "busy", "unix": now - 10},            # busy != fail
        {"probe": "fail", "unix": now - 100, "err": "hang"},
    ]))
    calls = []
    monkeypatch.setattr(b, "_run_one_child",
                        lambda argv, tmo: calls.append(argv) or (0, "", ""))
    ok, err = b._probe(now + 10_000)
    assert not ok and "skipped" in err and calls == []
    # and the negative memoizes for the invocation like a real probe
    ok2, err2 = b._probe(now + 10_000)
    assert not ok2 and calls == []


def test_probe_ledger_ok_supersedes_fail(tmp_path, monkeypatch):
    """A later "ok" (e.g. the watcher's) supersedes an earlier fail:
    the tunnel came back, so the probe runs."""
    b = _bench()
    import time as _t
    now = _t.time()
    monkeypatch.setattr(b, "PROBES_PATH", _write_probes(tmp_path, [
        {"probe": "fail", "unix": now - 300},
        {"probe": "ok", "unix": now - 50},
    ]))
    calls = []

    def fake_child(argv, tmo):
        calls.append(argv)
        return 0, "", ""

    monkeypatch.setattr(b, "_run_one_child", fake_child)
    ok, _err = b._probe(now + 10_000)
    assert ok and len(calls) == 1


def test_probe_ledger_stale_or_disabled_probes_again(tmp_path,
                                                     monkeypatch):
    b = _bench()
    import time as _t
    now = _t.time()
    path = _write_probes(tmp_path, [
        {"probe": "fail", "unix": now - 99999}])        # beyond TTL
    monkeypatch.setattr(b, "PROBES_PATH", path)
    calls = []
    monkeypatch.setattr(
        b, "_run_one_child",
        lambda argv, tmo: calls.append(argv) or (0, "", ""))
    ok, _err = b._probe(now + 10_000)
    assert ok and len(calls) == 1
    # TTL=0 disables the ledger read entirely, fresh failure or not
    b2 = _bench()
    monkeypatch.setattr(b2, "PROBES_PATH", _write_probes(
        tmp_path, [{"probe": "fail", "unix": now - 5}]))
    monkeypatch.setenv("BENCH_PROBE_NEG_TTL", "0")
    calls2 = []
    monkeypatch.setattr(
        b2, "_run_one_child",
        lambda argv, tmo: calls2.append(argv) or (0, "", ""))
    ok2, _e = b2._probe(now + 10_000)
    assert ok2 and len(calls2) == 1


def test_probe_outcomes_persist_to_ledger(tmp_path, monkeypatch):
    """A probe hang APPENDS a fail record (with unix stamp + err) in
    the watcher's line format, and a success appends ok — so the next
    invocation (and the availability ledger) both see it."""
    b = _bench()
    path = str(tmp_path / "probes.jsonl")
    monkeypatch.setattr(b, "PROBES_PATH", path)
    monkeypatch.setattr(b, "_run_one_child",
                        lambda argv, tmo: (None, "", ""))  # hang
    import time as _t
    ok, _err = b._probe(_t.time() + 10_000)
    assert not ok
    recs = [json.loads(x) for x in open(path)]
    assert recs[-1]["probe"] == "fail" and "unix" in recs[-1] \
        and "timeout" in recs[-1]["err"] and "t" in recs[-1]
    b2 = _bench()
    monkeypatch.setattr(b2, "PROBES_PATH", path)
    monkeypatch.setenv("BENCH_PROBE_NEG_TTL", "0")   # force a re-probe
    monkeypatch.setattr(b2, "_run_one_child",
                        lambda argv, tmo: (0, "", ""))
    ok2, _e = b2._probe(_t.time() + 10_000)
    assert ok2
    recs = [json.loads(x) for x in open(path)]
    assert recs[-1]["probe"] == "ok"


def test_probe_ledger_parses_watcher_iso_lines(tmp_path, monkeypatch):
    """The watcher writes {"t": ISO-8601, "probe": "fail"} with no
    unix stamp; those lines must gate bench probes too."""
    b = _bench()
    import time as _t
    now = _t.time()
    iso = _t.strftime("%Y-%m-%dT%H:%M:%SZ", _t.gmtime(now - 60))
    monkeypatch.setattr(b, "PROBES_PATH", _write_probes(
        tmp_path, [{"t": iso, "probe": "fail"}]))
    calls = []
    monkeypatch.setattr(
        b, "_run_one_child",
        lambda argv, tmo: calls.append(argv) or (0, "", ""))
    ok, err = b._probe(now + 10_000)
    assert not ok and "skipped" in err and calls == []


def test_probe_transient_rc_still_retries(monkeypatch, tmp_path):
    """A non-zero exit stays a transient: the retry loop (which fixed
    BENCH_r01) is untouched, and a retry that SUCCEEDS leaves no
    negative memo behind."""
    b = _bench()
    monkeypatch.setattr(b, "PROBES_PATH", str(tmp_path / "p.jsonl"))
    calls = []

    def fake_child(argv, tmo):
        calls.append(argv)
        return (1, "", "boom") if len(calls) == 1 else (0, "{}", "")

    monkeypatch.setattr(b, "_run_one_child", fake_child)
    monkeypatch.setattr(b, "PROBE_BACKOFF", 0)
    ok, _err = b._probe(__import__("time").time() + 10_000)
    assert ok and len(calls) == 2
    assert b._PROBE_NEG is None
