"""Channel-hostile robustness (ISSUE 15): the seeded physical-layer
profile subsystem — named multipath/SCO/Doppler/burst parameter sets
(phy/profiles) applied as vmapped per-lane taps through the impair
graphs — and the RX front-end hardening it exercises (bounded-|H|
null-subcarrier guard, pilot SCO phase-ramp tracking).

Contracts pinned here:

- `channel.multipath` vs a host numpy complex-FIR oracle (the helper
  had zero callers and zero tests before this PR);
- the profiled graph at NEUTRAL parameters is BIT-IDENTICAL to the
  unprofiled `impair_graph` (one-hot taps, zero-fraction resample,
  zero phase, zero burst amplitude are exact identities and the AWGN
  consumes the same lane key) — the flat-lane contract of mixed
  profiled batches;
- ``profile="flat"`` resolves to the UNPROFILED code path by
  construction: bit-identical streams/captures and ZERO new compiled
  programs, pinned across the loopback link (fused + staged), the
  streaming receiver, and the S=8 fleet at the suite-shared
  4096/1024/K=8 geometry under ``dispatch.no_recompile``;
- `impair_stream`'s noise draws follow the SAME per-lane fold-in key
  schedule as the batched graphs (the stream/batch seeding symmetry
  satellite);
- `sweep_ber`'s rates x SNR x PROFILE waterfall stays ONE `lax.scan`
  dispatch, its flat column is integer-identical to the unprofiled
  sweep, and the hostile profiles hold their BER envelopes at high
  SNR;
- the hostile-profile loopback agrees lane for lane across the
  staged / per-frame (and, slow, fused) modes;
- the bounded-|H| guard zeroes null bins exactly and is value-inert
  on healthy channels; `pilot_phase_correct(sco_track=True)` removes
  a synthetic phase ramp and measurably improves a strong-SCO decode.

Loopback geometry mirrors test_link_fused's exactly (same LENS/MBPS/
CFO/DELAY/SNRS, same B_SWEEP/NB_SWEEP sweep shape) so the unprofiled
programs are one compile class with that suite.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import channel, link
from ziria_tpu.phy import profiles as chanprof
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import RATES, n_symbols
from ziria_tpu.utils import dispatch, faults
from ziria_tpu.utils.bits import np_bytes_to_bits

# test_link_fused's exact loopback geometry: shared compile class
LENS = (16, 10, 16, 5, 16, 12, 9, 16)
MBPS = tuple(sorted(RATES))
CFO = tuple((-1) ** k * 1e-4 * (k + 1) for k in range(8))
DELAY = tuple(20 + 17 * k for k in range(8))
SNRS = (25.0, 30.0, -25.0, 28.0, 25.0, 30.0, 27.0, 26.0)

B_SWEEP, NB_SWEEP = 8, 24                  # test_link_fused geometry
SWEEP_RATES = (6, 54)

# the suite-shared streaming geometry (test_rx_stream / multistream)
CHUNK, FRAME_LEN, K = 4096, 1024, 8


# ------------------------------------------------------- registry/oracle


def test_multipath_matches_numpy_fir_oracle():
    # satellite 1: the orphaned helper, pinned against a float64
    # numpy complex FIR before anything builds on it
    rng = np.random.default_rng(3)
    x = rng.normal(size=(256, 2)).astype(np.float32)
    taps = rng.normal(size=(7, 2)).astype(np.float32)
    got = np.asarray(channel.multipath(x, taps))
    xc = x[:, 0].astype(np.float64) + 1j * x[:, 1].astype(np.float64)
    tc = taps[:, 0].astype(np.float64) + 1j * taps[:, 1] \
        .astype(np.float64)
    ref = np.convolve(xc, tc)[:256]
    np.testing.assert_allclose(got[:, 0], ref.real, atol=2e-4)
    np.testing.assert_allclose(got[:, 1], ref.imag, atol=2e-4)
    # one-hot taps are an exact identity (the flat-lane hinge)
    hot = np.zeros((5, 2), np.float32)
    hot[0, 0] = 1.0
    assert np.array_equal(np.asarray(channel.multipath(x, hot)), x)
    # and the host twin agrees with the device graph
    prof = chanprof.ChannelProfile(
        "t", taps=tuple((float(a), float(b)) for a, b in taps))
    np.testing.assert_allclose(chanprof.np_apply_taps(x, prof), got,
                               atol=2e-4)


def test_profile_registry_and_grammar():
    for name, prof in chanprof.CHANNEL_PROFILES.items():
        e = sum(r * r + i * i for r, i in prof.taps)
        assert abs(e - 1.0) < 1e-6, f"{name} taps not unit energy"
        assert len(prof.taps) <= 16, \
            f"{name} delay spread exceeds the cyclic prefix"
        assert prof.name == name
    assert chanprof.get_profile("flat").is_flat
    assert not chanprof.get_profile("severe").is_flat
    with pytest.raises(ValueError, match="known:"):
        chanprof.get_profile("nope")
    assert chanprof.parse_profile_spec(" flat , severe ") == \
        ("flat", "severe")
    with pytest.raises(ValueError):
        chanprof.parse_profile_spec("flat,nope")
    # flat resolves to the UNPROFILED path; mixes cycle per lane
    assert chanprof.resolve_profiles("flat", 4) is None
    assert chanprof.resolve_profiles(None, 4, use_env=False) is None
    assert chanprof.resolve_profiles(("mild", "severe"), 4) == \
        ("mild", "severe", "mild", "severe")


def test_env_knob_scoping(monkeypatch):
    psdus = [np.arange(12, dtype=np.uint8)] * 2
    base, _ = link.stream_many(psdus, [6, 24], gaps=[400],
                               snr_db=np.inf, seed=4, add_fcs=True)
    monkeypatch.setenv("ZIRIA_CHANNEL_PROFILE", "severe")
    via_env, _ = link.stream_many(psdus, [6, 24], gaps=[400],
                                  snr_db=np.inf, seed=4, add_fcs=True)
    explicit, _ = link.stream_many(psdus, [6, 24], gaps=[400],
                                   snr_db=np.inf, seed=4,
                                   add_fcs=True,
                                   channel_profile="severe")
    # env default == explicit request; explicit "flat" OVERRIDES the
    # env (the resolve-once precedence rule — a lower layer must not
    # resurrect the env default a surface already consumed)
    assert np.array_equal(via_env, explicit)
    assert not np.array_equal(via_env, base)
    flat, _ = link.stream_many(psdus, [6, 24], gaps=[400],
                               snr_db=np.inf, seed=4, add_fcs=True,
                               channel_profile="flat")
    assert np.array_equal(flat, base)
    monkeypatch.delenv("ZIRIA_CHANNEL_PROFILE")
    assert np.array_equal(
        link.stream_many(psdus, [6, 24], gaps=[400], snr_db=np.inf,
                         seed=4, add_fcs=True)[0], base)


# ------------------------------------------- graph neutral-identity


def test_neutral_profile_graph_bit_identical():
    # the flat-lane contract: the PROFILED graph at neutral
    # parameters reproduces impair_graph BITWISE (every added op is
    # an exact identity; the AWGN consumes the same lane key)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(512, 2)).astype(np.float32)
    key = channel.lane_key(3, 0)
    a = np.asarray(channel.impair_graph(x, 400, 20.0, 1e-3, 30, key))
    arrs = [jnp.asarray(v) for v in chanprof.lane_arrays(("flat",))]
    b = np.asarray(channel.impair_profile_graph(
        x, 400, 20.0, 1e-3, 30, key, *[v[0] for v in arrs]))
    assert np.array_equal(a, b)


def test_mixed_batch_flat_lane_and_per_frame_oracle():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(2, 400, 2)).astype(np.float32)
    xb = jnp.asarray(x)
    plain = np.asarray(channel.impair_many(xb, 400, 20.0, 1e-3, 16,
                                           seed=9, out_len=512))
    mixed = np.asarray(channel.impair_many(
        xb, 400, 20.0, 1e-3, 16, seed=9, out_len=512,
        profile=("flat", "severe")))
    # the flat lane of a MIXED profiled batch: the neutral ops are
    # EXACT identities and the AWGN key is the same (the eager graph
    # is pinned bitwise above), but the profiled batch is a
    # separately-COMPILED program and XLA's FMA contraction may round
    # the shared ops differently — so the cross-program pin is one
    # float32 ulp, while the severe lane genuinely differs
    np.testing.assert_allclose(mixed[0], plain[0], atol=3e-7,
                               rtol=0.0)
    assert not np.allclose(mixed[1], plain[1], atol=1e-3)
    # per-frame oracle == its batched lane, profile included (same
    # ulp rule: single-lane and vmapped programs compile separately)
    one = np.asarray(channel.impair_one(x[1], 20.0, 1e-3, 16, 9, 1,
                                        512, profile="severe"))
    np.testing.assert_allclose(one, mixed[1], atol=3e-7, rtol=0.0)
    # determinism: the same profiled batch replays bitwise
    again = np.asarray(channel.impair_many(
        xb, 400, 20.0, 1e-3, 16, seed=9, out_len=512,
        profile=("flat", "severe")))
    assert np.array_equal(again, mixed)


def test_impair_stream_seeding_symmetry():
    # satellite 2: the stream AWGN follows the SAME per-lane fold-in
    # schedule as the batched graphs — jax.random.normal off
    # lane_key(seed, lane), element-identical at equal geometry
    rng = np.random.default_rng(2)
    x = rng.normal(size=(512, 2)).astype(np.float32)
    got = channel.impair_stream(x, x.shape[0], 20.0, 0.0, seed=7)
    p_sig = float(np.sum(x.astype(np.float64) ** 2) / x.shape[0])
    scale = np.sqrt(p_sig / 10.0 ** 2 / 2.0)
    for lane, out in ((0, got),
                      (3, channel.impair_stream(x, x.shape[0], 20.0,
                                                0.0, seed=7,
                                                lane=3))):
        want = (x + np.asarray(
            jax.random.normal(channel.lane_key(7, lane), x.shape),
            np.float64) * scale).astype(np.float32)
        assert np.array_equal(out, want), f"lane {lane}"
    assert not np.array_equal(
        got, channel.impair_stream(x, x.shape[0], 20.0, 0.0, seed=7,
                                   lane=3))


# -------------------------------------------------- loopback identity


def _loop(profile=None, **kw):
    rng = np.random.default_rng(20260803)
    psdus = [rng.integers(0, 256, n).astype(np.uint8) for n in LENS]
    got = link.loopback_many(psdus, MBPS, snr_db=SNRS, cfo=CFO,
                             delay=DELAY, seed=11, add_fcs=True,
                             check_fcs=True, channel_profile=profile,
                             **kw)
    return psdus, got


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


def test_loopback_flat_identity_zero_new_programs():
    # profile="flat" IS the unprofiled link: bit-identical results
    # AND zero new compiled programs, fused and staged alike
    _p, base_fu = _loop(fused=True)
    _p, base_st = _loop(fused=False)
    with dispatch.no_recompile(link._jit_fused_link,
                               channel._jit_impair_many,
                               rx._jit_decode_data_mixed,
                               rx._jit_acquire_many):
        _p, flat_fu = _loop(profile="flat", fused=True)
        _p, flat_st = _loop(profile="flat", fused=False)
    for a, b in zip(flat_fu, base_fu):
        assert _same_result(a, b)
    for a, b in zip(flat_st, base_st):
        assert _same_result(a, b)


def test_loopback_hostile_staged_equals_per_frame():
    # per-lane MIXED profiles through the staged batch vs the
    # per-frame oracle loop: lane-for-lane identical RxResults (the
    # profiled channel is the same graph with the same fold-in keys
    # either way; the decode programs are the already-compiled ones)
    profs = ("severe", "urban", "flat", "mild", "severe", "urban",
             "mild", "flat")
    psdus, staged = _loop(profile=profs, fused=False)
    _p, perframe = _loop(profile=profs, batched_tx=False)
    assert len(staged) == len(perframe) == len(psdus)
    for a, b in zip(staged, perframe):
        assert _same_result(a, b)
    # the equalizable profiles decode clean at these SNRs (lane 2 is
    # the swamped -25 dB lane, failed in BOTH paths by construction)
    for k in (0, 1, 3, 4, 5, 6, 7):
        assert staged[k].ok and staged[k].crc_ok, k


@pytest.mark.slow
def test_loopback_hostile_fused_equals_staged():
    # the profiled FUSED graph (one dispatch, profile constants baked
    # in) against the staged oracle — heavy compile, tier-2
    profs = ("severe", "urban", "flat", "mild", "severe", "urban",
             "mild", "flat")
    _p, fused = _loop(profile=profs, fused=True)
    _p, staged = _loop(profile=profs, fused=False)
    for a, b in zip(fused, staged):
        assert _same_result(a, b)


# ------------------------------------------------------- sweep profile axis


@pytest.fixture(scope="module")
def sweep_corpus():
    rng = np.random.default_rng(9)
    psdus = rng.integers(0, 256, (B_SWEEP, NB_SWEEP)).astype(np.uint8)
    snrs, seeds = (8.0, 30.0), (7,)
    profiles = ("flat", "severe", "bursty")
    base = link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds)
    with dispatch.count_dispatches() as d_sw:
        errs = link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds,
                              profiles=profiles)
    return psdus, snrs, seeds, profiles, base, errs, d_sw


def test_sweep_profile_axis_one_dispatch(sweep_corpus):
    _p, snrs, seeds, profiles, _b, errs, d_sw = sweep_corpus
    assert errs.shape == (len(SWEEP_RATES), len(profiles), len(snrs),
                          len(seeds))
    assert d_sw.total <= 1, dict(d_sw.counts)
    assert d_sw.counts["link.sweep"] == 1


def test_sweep_flat_column_identical(sweep_corpus):
    # the flat column IS the unprofiled sweep — integer-identical
    _p, _s, _k, profiles, base, errs, _d = sweep_corpus
    assert np.array_equal(errs[:, profiles.index("flat")], base)


def test_sweep_hostile_envelopes(sweep_corpus):
    # bounded error floors at the 30 dB point (the acceptance gate;
    # the bench channel_sweep stage runs the full profile set)
    psdus, _s, seeds, profiles, _b, errs, _d = sweep_corpus
    bits = B_SWEEP * 8 * NB_SWEEP * len(SWEEP_RATES) * len(seeds)
    floor = {p: float(errs[:, i, -1, :].sum()) / bits
             for i, p in enumerate(profiles)}
    assert floor["flat"] == 0.0, floor
    assert floor["severe"] <= 0.15, floor
    assert floor["bursty"] <= 0.30, floor
    # and the waterfall falls: no profile's BER rises with SNR
    for i, p in enumerate(profiles):
        ber = errs[:, i].sum(axis=(0, 2)) / bits
        assert ber[1] <= ber[0] + 2e-3, (p, ber)


@pytest.mark.slow
def test_sweep_profiled_equals_perbatch_loop(sweep_corpus):
    # the degraded twin stays integer-identical under the profile
    # axis: loopback_ber_bits(profile=...) applies the same point
    # graph at the same split keys
    psdus, snrs, seeds, profiles, _b, errs, _d = sweep_corpus
    bits = np.stack([np_bytes_to_bits(p) for p in psdus])
    for pi, pname in enumerate(profiles):
        for si, s in enumerate(snrs):
            for ki, sd in enumerate(seeds):
                for ri, m in enumerate(SWEEP_RATES):
                    got = link.loopback_ber_bits(
                        psdus, m, float(s), int(sd), profile=pname)
                    assert int((got != bits).sum()) == \
                        int(errs[ri, pi, si, ki]), (pname, m, s)


# ----------------------------------------------------- RX hardening


def test_h_guard_nulls_exactly_and_is_inert_when_healthy():
    rng = np.random.default_rng(6)
    data = rng.normal(size=(2, 48, 2)).astype(np.float32)
    pilots = rng.normal(size=(2, 4, 2)).astype(np.float32)
    h = np.ones((64, 2), np.float32)
    # healthy flat channel: everything passes through BITWISE
    d2, p2, g2 = rx.guard_subcarriers(jnp.asarray(data),
                                      jnp.asarray(pilots),
                                      jnp.asarray(h))
    assert np.array_equal(np.asarray(d2), data)
    assert np.array_equal(np.asarray(p2), pilots)
    # null one data bin and one pilot bin: exact-zero erasures there,
    # every other value untouched
    from ziria_tpu.ops import ofdm
    hn = h.copy()
    hn[ofdm.DATA_BINS[5]] = 1e-6
    hn[ofdm.PILOT_BINS[2]] = 0.0
    d3, p3, g3 = rx.guard_subcarriers(jnp.asarray(data),
                                      jnp.asarray(pilots),
                                      jnp.asarray(hn))
    d3, p3, g3 = np.asarray(d3), np.asarray(p3), np.asarray(g3)
    assert np.all(d3[:, 5] == 0.0) and g3[5] == 0.0
    assert np.all(p3[:, 2] == 0.0)
    keep = [i for i in range(48) if i != 5]
    assert np.array_equal(d3[:, keep], data[:, keep])
    assert np.array_equal(p3[:, [0, 1, 3]], pilots[:, [0, 1, 3]])
    assert np.all(g3[keep] > 0.0)


def test_pilot_sco_track_removes_phase_ramp():
    from ziria_tpu.ops import ofdm
    rng = np.random.default_rng(8)
    n_sym = 4
    syms = (rng.integers(0, 2, (n_sym, 48, 2)) * 2 - 1) \
        .astype(np.float32) / np.sqrt(2.0)
    pol = ofdm.PILOT_POLARITY[(np.arange(n_sym) + 1) % 127]
    pilots_re = (ofdm.PILOT_VALS[None, :] * pol[:, None]) \
        .astype(np.float32)
    pilots = np.stack([pilots_re, np.zeros_like(pilots_re)], axis=-1)
    # apply a per-subcarrier phase ramp growing over the symbols (the
    # SCO signature) to data AND pilots
    slope = 0.004 * (1.0 + np.arange(n_sym))            # rad/subcarrier
    def rot(x, k):
        th = slope[:, None] * k[None, :]
        c, s = np.cos(th), np.sin(th)
        return np.stack([x[..., 0] * c - x[..., 1] * s,
                         x[..., 0] * s + x[..., 1] * c], axis=-1) \
            .astype(np.float32)
    data_r = rot(syms, ofdm.DATA_SC.astype(np.float64))
    pilots_r = rot(pilots, ofdm.PILOT_SC.astype(np.float64))
    off = np.asarray(rx.pilot_phase_correct(
        jnp.asarray(data_r), jnp.asarray(pilots_r), 1,
        sco_track=False))
    on = np.asarray(rx.pilot_phase_correct(
        jnp.asarray(data_r), jnp.asarray(pilots_r), 1,
        sco_track=True))
    def worst(x):
        ph = np.abs(np.arctan2(
            (x[..., 0] * syms[..., 1] - x[..., 1] * syms[..., 0]),
            (x[..., 0] * syms[..., 0] + x[..., 1] * syms[..., 1])))
        return float(ph.max())
    # tracking removes the ramp (residual < 10% of the edge phase);
    # without it the band edge keeps ~slope * 26 of error
    assert worst(on) < 0.1 * worst(off)
    assert worst(off) > 0.2


def test_sco_track_improves_strong_sco_decode():
    # end-to-end: a 400 ppm clock offset at 54 Mbps (64-QAM) — the
    # phase ramp at the band edge breaks the untracked decode, the
    # tracked one recovers most of it
    rng = np.random.default_rng(5)
    b, n_bytes, m = 2, 60, 54
    psdus = rng.integers(0, 256, (b, n_bytes)).astype(np.uint8)
    want = np.stack([np_bytes_to_bits(p) for p in psdus])
    frames = jnp.asarray(np.asarray(tx.encode_batch(psdus, m)))
    n_sym = n_symbols(n_bytes, RATES[m])
    x = jax.vmap(lambda f: channel.sco_resample_graph(f, 4e-4))(
        frames)
    errs = {}
    for st in (False, True):
        got, _ = rx.decode_data_batch(x, RATES[m], n_sym,
                                      8 * n_bytes, sco_track=st)
        errs[st] = int(np.sum(np.asarray(got) != want))
    assert errs[False] > 50, errs       # the fault is real
    assert errs[True] < errs[False] // 4, errs


# ------------------------------------------- streaming / fleet / chaos


def _std_streams(s, profile, seed=31):
    rng = np.random.default_rng(seed)
    psdus = [[rng.integers(0, 256, 12).astype(np.uint8)
              for _ in range(2)] for _ in range(s)]
    rates = [[MBPS[(i + j) % 8] for j in range(2)] for i in range(s)]
    return link.stream_many_multi(
        psdus, rates, snr_db=30.0, cfo=1e-4, delay=60, seed=seed,
        add_fcs=True, tail=FRAME_LEN, channel_profile=profile)


def test_fleet_flat_identity_no_recompile():
    # S=8 fleet at the suite-shared geometry: flat-profile streams
    # are bitwise the unprofiled streams, and decoding them mints no
    # new compiled programs (warm pass first — the fleet programs are
    # the suite-shared compile class)
    streams, starts = _std_streams(8, None)
    flat_streams, fstarts = _std_streams(8, "flat")
    for a, b in zip(streams, flat_streams):
        assert np.array_equal(a, b)
    for a, b in zip(starts, fstarts):
        assert np.array_equal(a, b)
    kw = dict(chunk_len=CHUNK, frame_len=FRAME_LEN,
              max_frames_per_chunk=K, check_fcs=True)
    base, _stats = framebatch.receive_streams(streams, **kw)
    with dispatch.no_recompile(rx._jit_stream_chunk_multi,
                               rx._jit_stream_decode_multi):
        got, stats = framebatch.receive_streams(flat_streams, **kw)
    assert sum(len(v) for v in got) == sum(len(v) for v in base) > 0
    for gs, bs in zip(got, base):
        for a, b in zip(gs, bs):
            assert a.start == b.start
            assert _same_result(a.result, b.result)


def test_hostile_stream_and_channel_chaos_contained():
    # a hostile-profile stream AND chaos channel-kind slab corruption
    # through the streaming receiver: frames may fail, the receiver
    # may not crash, healthy runs stay healthy (docs/robustness.md)
    (stream,), (starts,) = _std_streams(1, "hostile", seed=33)
    sr = framebatch.StreamReceiver(chunk_len=CHUNK,
                                   frame_len=FRAME_LEN,
                                   max_frames_per_chunk=K,
                                   check_fcs=True, sanitize=True)
    got = sr.push(stream)
    got += sr.flush()
    assert sr.stats.chunks > 0          # it ran, it did not crash
    # chaos grammar: per-slab channel corruption at the push seam
    (clean,), _ = _std_streams(1, None, seed=33)
    specs, cseed = faults.parse_chaos_spec(
        "seed=5;rx.push:channel:profile=severe,every=2")
    sr2 = framebatch.StreamReceiver(chunk_len=CHUNK,
                                    frame_len=FRAME_LEN,
                                    max_frames_per_chunk=K,
                                    check_fcs=True, sanitize=True)
    with faults.inject(*specs, seed=cseed) as plan:
        out = []
        for lo in range(0, clean.shape[0], 1500):
            out += sr2.push(clean[lo: lo + 1500])
        out += sr2.flush()
    assert plan.total_fired > 0
    assert sr2.stats.chunks > 0         # corrupted input, no crash
