"""RX per-block surface programs vs the ops/ oracles.

The golden files (examples/golden/) pin interp == jit on fixed inputs;
these tests pin the *semantics*: each .zir RX block must match the
corresponding ziria_tpu/ops implementation the receiver actually uses
(VERDICT r1 #7 — the reference's densest per-block test area,
SURVEY.md §2.3)."""

import os

import numpy as np
import pytest

from ziria_tpu.frontend import compile_file
from ziria_tpu.interp.interp import run
from ziria_tpu.ops import coding, demap as demap_mod, interleave
from ziria_tpu.utils.diff import assert_stream_eq

HERE = os.path.dirname(__file__)
EXAMPLES = os.path.abspath(os.path.join(HERE, "..", "examples"))

RNG = np.random.default_rng(42)


def _run_zir(name, xs):
    prog = compile_file(os.path.join(EXAMPLES, f"{name}.zir"))
    res = run(prog.comp, list(xs))
    return np.asarray(res.out_array())


def _iq(n):
    return RNG.integers(-600, 600, (n, 2)).astype(np.int16)


@pytest.mark.parametrize("name,n_bpsc", [
    ("demap_bpsk", 1), ("demap_qpsk", 2),
    ("demap_qam16", 4), ("demap_qam64", 6),
])
def test_demap_blocks_match_ops(name, n_bpsc):
    iq = _iq(96)
    got = _run_zir(name, iq)
    want = np.asarray(demap_mod.demap(iq.astype(np.float32) / 512.0,
                                      n_bpsc))
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_deinterleave_bpsk_matches_ops():
    bits = RNG.integers(0, 2, 480).astype(np.uint8)
    got = _run_zir("deinterleave_bpsk", bits)
    want = np.asarray(interleave.deinterleave(bits, 48, 1))
    assert_stream_eq(got, want, name="deint48")
    # and it inverts the TX interleaver block
    inter = _run_zir("interleaver", bits)
    back = _run_zir("deinterleave_bpsk", inter.astype(np.uint8))
    assert_stream_eq(back, bits, name="roundtrip48")


def test_deinterleave_qam16_matches_ops():
    llrs = RNG.standard_normal(192 * 3).astype(np.float32)
    got = _run_zir("deinterleave_qam16", llrs)
    want = np.asarray(interleave.deinterleave(llrs, 192, 4))
    np.testing.assert_allclose(got, want, atol=0)


@pytest.mark.parametrize("name,rate", [
    ("depuncture_23", "2/3"), ("depuncture_34", "3/4"),
])
def test_depuncture_blocks_match_ops(name, rate):
    llrs = RNG.standard_normal(96).astype(np.float32)
    got = _run_zir(name, llrs)
    want = np.asarray(coding.depuncture(llrs, rate, fill=0.0)).reshape(-1)
    np.testing.assert_allclose(got, want, atol=0)


def test_pilot_track_matches_rx_oracle():
    """The in-language pilot tracker == rx.pilot_phase_correct on the
    same (data, pilots) layout, up to the int16 requantization."""
    from ziria_tpu.phy.wifi.rx import pilot_phase_correct

    n_sym = 5
    iq = _iq(52 * n_sym)
    got = _run_zir("pilot_track", iq).reshape(n_sym, 48, 2)

    sym = iq.astype(np.float32).reshape(n_sym, 52, 2) / 1.0
    data = sym[:, :48]
    pilots = sym[:, 48:]
    want = np.asarray(pilot_phase_correct(data, pilots, symbol_index0=0))
    np.testing.assert_allclose(got, np.round(want), atol=1.0)


def test_crc_frame_matches_ops():
    """The crc32 stdlib external through a .zir program == ops/crc.py
    append_crc32 per frame."""
    from ziria_tpu.ops.crc import append_crc32

    bits = RNG.integers(0, 2, 512).astype(np.uint8)
    got = _run_zir("crc_frame", bits)
    want = np.concatenate([np.asarray(append_crc32(bits[:256])),
                           np.asarray(append_crc32(bits[256:]))])
    assert_stream_eq(got, want, name="crc_frame")


def test_correlator_matches_numpy():
    """The v_conj_mul + v_sum_window detector block == direct numpy."""
    iq = _iq(320)
    got = _run_zir("correlator", iq)
    x = (iq[:, 0] + 1j * iq[:, 1]).astype(np.complex64)
    want = []
    for blk in (x[:160], x[160:]):
        m = blk[16:160] * np.conj(blk[0:144])
        s = np.array([m[k:k + 16].sum() for k in range(129)])
        want.append(np.abs(s) / (512.0 * 512.0))
    np.testing.assert_allclose(got, np.concatenate(want), rtol=2e-5,
                               atol=1e-4)


def test_dc_remove_kills_offset():
    """dc_remove.zir (reference RX front-end block): a strong DC
    offset decays with the single-pole IIR's time constant and an
    oracle numpy recurrence reproduces the stream exactly."""
    rng = np.random.default_rng(7)
    x = (rng.normal(0, 120, (1024, 2))
         + np.array([310.0, -170.0]))
    x = np.clip(np.round(x), -32768, 32767).astype(np.int16)
    got = _run_zir("dc_remove", x)
    got = np.asarray(got)

    # numpy oracle: acc += (x - acc/64); y = x - acc/64
    acc = np.zeros(2)
    want = np.empty_like(x, dtype=np.float64)
    for k in range(x.shape[0]):
        acc = acc + (x[k] - acc / 64.0)
        want[k] = x[k] - acc / 64.0
    # complex16 output quantizes to int16
    np.testing.assert_array_equal(
        got, np.clip(np.round(want), -32768, 32767).astype(np.int16))
    # and the offset is actually gone in the tail
    tail = got[512:].mean(axis=0)
    assert np.all(np.abs(tail) < 15), tail
