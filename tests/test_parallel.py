"""Stage-parallel (|>>>|) and frame-batching (dp) execution on the
8-virtual-device CPU mesh — outputs must equal the fused single-device
lowering (the reference's invariant: |>>>| output == >>> output)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.lower import lower
from ziria_tpu.core import ir
from ziria_tpu.parallel import (data_parallel, frame_mesh,
                                lower_stage_parallel, shard_batch)
from jax.sharding import Mesh


def _mesh(n, axis="pp"):
    devs = jax.devices()[:n]
    return Mesh(np.array(devs), (axis,))


def _run_fused(comp, xs_chunks):
    lo = lower(comp, width=1)
    carry = lo.init_carry
    outs = []
    for c in xs_chunks:
        carry, y = jax.jit(lo.step)(carry, c)
        outs.append(np.asarray(y))
    return np.stack(outs)


def test_two_stage_matches_fused():
    a = z.zmap(lambda x: x * 2.0, name="dbl")
    b = z.zmap(lambda x: x + 1.0, name="inc")
    comp = z.par_pipe(a, b)

    pp = lower_stage_parallel(comp, _mesh(2), width=4)
    M = 6
    xs = np.arange(M * pp.take, dtype=np.float32).reshape(M, pp.take)
    got = np.asarray(pp.run(xs))
    want = _run_fused(ir.Pipe(a, b), jnp.asarray(xs))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_stateful_stage_carries_across_macro_steps():
    # stage 1: running sum (stateful); stage 2: negate
    acc = z.map_accum(lambda s, x: (s + x, s + x), 0.0, name="cumsum")
    neg = z.zmap(lambda x: -x, name="neg")
    comp = z.par_pipe(acc, neg)

    pp = lower_stage_parallel(comp, _mesh(2), width=3)
    M = 5
    xs = np.arange(M * pp.take, dtype=np.float32).reshape(M, pp.take)
    got = np.asarray(pp.run(xs)).reshape(-1)
    want = -np.cumsum(xs.reshape(-1))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_stateful_downstream_stage_ignores_fill_bubbles():
    # stage 2 is stateful with a transition that is NOT identity on zero
    # input — fill bubbles must not advance its state (regression: the
    # first macro step used to step downstream carries on zeros)
    neg = z.zmap(lambda x: -x, name="neg")
    ctr = z.map_accum(lambda s, x: (s + 1.0, x + s), 0.0, name="ctr")
    comp = z.par_pipe(neg, ctr)

    pp = lower_stage_parallel(comp, _mesh(2), width=2)
    M = 4
    xs = np.arange(M * pp.take, dtype=np.float32).reshape(M, pp.take)
    got = np.asarray(pp.run(xs)).reshape(-1)
    flat = xs.reshape(-1)
    want = -flat + np.arange(flat.size, dtype=np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_rate_mismatched_stages():
    # stage 1 emits pairs, stage 2 sums pairs -> rates 1:2 vs 2:1
    dup = z.repeat(z.let("x", z.take,
                         z.emits(lambda e: jnp.stack([e["x"], e["x"]]), 2)))
    pair_sum = z.repeat(z.let("p", z.takes(2),
                              z.emit1(lambda e: e["p"][0] + e["p"][1])))
    comp = z.par_pipe(dup, pair_sum)

    pp = lower_stage_parallel(comp, _mesh(2), width=2)
    M = 4
    xs = np.arange(M * pp.take, dtype=np.float32).reshape(M, pp.take)
    got = np.asarray(pp.run(xs)).reshape(-1)
    np.testing.assert_allclose(got, 2.0 * xs.reshape(-1), rtol=1e-6)


def test_four_stages_int_dtype_preserved():
    stages = [z.zmap(lambda x, _k=k: x + _k, name=f"s{k}") for k in range(4)]
    comp = z.par_pipe(*stages)
    pp = lower_stage_parallel(
        comp, _mesh(4), width=2,
        in_item=jax.ShapeDtypeStruct((), jnp.int32))
    M = 3
    xs = np.arange(M * pp.take, dtype=np.int32).reshape(M, pp.take)
    got = np.asarray(pp.run(xs))
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, xs + 6)


def test_wrong_segment_count_raises():
    comp = z.par_pipe(z.zmap(lambda x: x), z.zmap(lambda x: x))
    from ziria_tpu.backend.lower import LowerError
    with pytest.raises(LowerError):
        lower_stage_parallel(comp, _mesh(3), width=1)


def test_data_parallel_frames():
    mesh = frame_mesh(8)
    B = 16
    x = np.arange(B * 32, dtype=np.float32).reshape(B, 32)
    xs = shard_batch(mesh, x)
    fn = data_parallel(lambda a: (a * 2).sum(axis=-1), mesh)
    got = np.asarray(fn(xs))
    np.testing.assert_allclose(got, (x * 2).sum(-1), rtol=1e-6)


def test_stage_parallel_2d_dp_x_pp():
    """Batched streams over 'dp' each flowing through a 'pp'
    stage-parallel pipeline on one 2-D mesh (frame batching x stage
    parallelism composed — SURVEY.md §2.4)."""
    import jax
    from jax.sharding import Mesh
    import numpy as np
    import ziria_tpu as z
    from ziria_tpu.parallel import lower_stage_parallel

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "pp"))

    # 4 stages: affine transforms + a stateful cumsum to prove carries
    # stay per-stream
    stages = [
        z.zmap(lambda x: x * 2.0, name="s0"),
        z.map_accum(lambda s, x: (s + x, s + x), 0.0, name="cumsum"),
        z.zmap(lambda x: x + 1.0, name="s2"),
        z.zmap(lambda x: x * 0.5, name="s3"),
    ]
    pp = lower_stage_parallel(z.par_pipe(*stages), mesh, width=4,
                              batch_axis="dp")

    B, M = 6, 5              # 6 streams (not a multiple of dp=2 shards
    #                          per row? 6/2=3 per device — fine)
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(B, M, pp.take)).astype(np.float32)

    from ziria_tpu.parallel import shard_batch
    ys = np.asarray(pp.run(shard_batch(mesh, xs, axis="dp")))
    assert ys.shape[:2] == (B, M)

    # oracle: per-stream sequential semantics
    for b in range(B):
        flat = xs[b].reshape(-1)
        cs = np.cumsum(flat * 2.0)
        want = ((cs + 1.0) * 0.5).reshape(M, -1)
        np.testing.assert_allclose(ys[b], want, rtol=1e-5, atol=1e-5,
                                   err_msg=f"stream {b}")


def test_compile_time_scaling_bounded():
    """VERDICT r1 weak #4: each device compiles all K switch branches
    (program size O(K x segments)); pin that compile time stays within
    a small factor going K=2 -> K=8 so a regression to super-linear
    blowup fails loudly."""
    import time

    def build_and_time(K):
        mesh = _mesh(K)
        stages = [z.zmap(lambda x, _k=k: x * 1.5 + _k, name=f"s{k}")
                  for k in range(K)]
        pp = lower_stage_parallel(z.par_pipe(*stages), mesh, width=8)
        xs = np.arange(6 * pp.take, dtype=np.float32).reshape(
            6, pp.take)
        t0 = time.perf_counter()
        np.asarray(pp.run(xs))
        return time.perf_counter() - t0

    build_and_time(2)           # warm-up: absorb first-touch overhead
    times = {K: build_and_time(K) for K in (2, 8)}
    # measured ~1.4x on this suite's virtual mesh; 6x headroom guards
    # against environmental noise while still catching K^2-style blowup
    assert times[8] < 6 * times[2] + 2.0, times


def test_pp_exit_carries_match_sequential():
    # run_carry: drain bubbles must NOT corrupt segment exit carries;
    # the flattened carry must continue the fused single-device path
    # exactly (the --pp remainder mechanism, VERDICT r2 #5)
    from ziria_tpu.backend.execute import run_jit_carry
    acc = z.map_accum(lambda s, x: (s + x, s + x), 0.0, name="cumsum")
    ctr = z.map_accum(lambda s, x: (s + 1.0, x + s), 0.0, name="ctr")
    comp = z.par_pipe(acc, ctr)
    pp = lower_stage_parallel(comp, _mesh(2), width=2)
    M = 5
    xs = np.arange(M * pp.take, dtype=np.float32).reshape(M, pp.take)
    got, carry = pp.run_carry(xs)
    seq = ir.Pipe(acc, ctr)
    want = _run_fused(seq, jnp.asarray(xs))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
    # continue with a tail through the returned carry
    tail_items = np.arange(7, dtype=np.float32) + 1000.0
    tail_got, _ = run_jit_carry(seq, tail_items, carry=carry, width=1)
    full = np.concatenate([xs.reshape(-1), tail_items])
    full_want, _ = run_jit_carry(seq, full, width=1)
    np.testing.assert_allclose(
        np.concatenate([np.asarray(got).reshape(-1),
                        np.asarray(tail_got).reshape(-1)]),
        np.asarray(full_want).reshape(-1), rtol=1e-6)


def test_cli_pp_ragged_length(tmp_path):
    # end-to-end: --pp with a stream length that does NOT divide the
    # macro chunk must equal the fused run (same flags, no --pp)
    from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                           write_stream)
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "p.zir"
    src.write_text("""
fun inc(x: int32) : int32 { return x + 1 }
fun dbl(x: int32) : int32 { return x * 2 }
let comp main = read[int32] >>> map inc |>>>| map dbl >>> write[int32]
""")
    xs = (np.arange(8 * 16 + 11, dtype=np.int32) * 3) % 257
    inf, outf, outf2 = (tmp_path / n for n in
                        ("in.bin", "pp.bin", "seq.bin"))
    write_stream(StreamSpec(ty="int32", path=str(inf), mode="bin"), xs)
    base = [f"--src={src}", "--input=file",
            f"--input-file-name={inf}", "--input-file-mode=bin",
            "--output=file", "--output-file-mode=bin"]
    assert cli_main(base + [f"--output-file-name={outf}", "--pp=2",
                            "--width=8"]) == 0
    assert cli_main(base + [f"--output-file-name={outf2}"]) == 0
    got = read_stream(StreamSpec(ty="int32", path=str(outf), mode="bin"))
    want = read_stream(StreamSpec(ty="int32", path=str(outf2),
                                  mode="bin"))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_cli_pp_shorter_than_one_macro_chunk(tmp_path):
    from ziria_tpu.runtime.buffers import (StreamSpec, read_stream,
                                           write_stream)
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "p.zir"
    src.write_text("""
fun inc(x: int32) : int32 { return x + 1 }
fun dbl(x: int32) : int32 { return x * 2 }
let comp main = read[int32] >>> map inc |>>>| map dbl >>> write[int32]
""")
    xs = np.arange(5, dtype=np.int32)      # < one macro chunk
    inf, outf = tmp_path / "in.bin", tmp_path / "out.bin"
    write_stream(StreamSpec(ty="int32", path=str(inf), mode="bin"), xs)
    rc = cli_main([f"--src={src}", "--input=file",
                   f"--input-file-name={inf}", "--input-file-mode=bin",
                   "--output=file", f"--output-file-name={outf}",
                   "--output-file-mode=bin", "--pp=2", "--width=8"])
    assert rc == 0
    got = read_stream(StreamSpec(ty="int32", path=str(outf), mode="bin"))
    np.testing.assert_array_equal(np.asarray(got), (xs + 1) * 2)


def test_dp_x_pp_per_stream_exit_carries():
    """VERDICT r3 next #6: the batched (dp x pp) path exposes one exit
    carry per stream, so each stream's ragged remainder can continue on
    the single-device path — exact equality with the per-stream fused
    run over bulk + remainder."""
    import jax
    from jax.sharding import Mesh
    import ziria_tpu as z
    from ziria_tpu.backend.execute import run_jit, run_jit_carry
    from ziria_tpu.parallel import lower_stage_parallel
    from ziria_tpu.parallel import shard_batch

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "pp"))
    stages = [
        z.zmap(lambda x: x * 2.0, name="s0"),
        z.map_accum(lambda s, x: (s + x, s + x), 0.0, name="cumsum"),
        z.zmap(lambda x: x + 1.0, name="s2"),
        z.map_accum(lambda s, x: (s + 1.0, x + s), 0.0, name="ctr"),
    ]
    comp = z.par_pipe(*stages)
    pp = lower_stage_parallel(comp, mesh, width=4, batch_axis="dp")

    B, M, rem_items = 4, 5, 7
    rng = np.random.default_rng(3)
    bulk = rng.normal(size=(B, M, pp.take)).astype(np.float32)
    rems = rng.normal(size=(B, rem_items)).astype(np.float32)

    ys, carries = pp.run_carry(shard_batch(mesh, bulk, axis="dp"))
    ys = np.asarray(ys)
    assert isinstance(carries, list) and len(carries) == B

    fused = z.pipe(*stages)
    for b in range(B):
        tail, _ = run_jit_carry(fused, rems[b], carry=carries[b])
        got = np.concatenate([ys[b].reshape(-1), np.asarray(tail)])
        want = run_jit(fused, np.concatenate(
            [bulk[b].reshape(-1), rems[b]]))
        np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5,
                                   atol=1e-5, err_msg=f"stream {b}")
