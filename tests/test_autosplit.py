"""Auto-pipelining (parallel/autosplit.py): the compiler decides the
|>>>| placement — balanced contiguous partition of the stage list —
and the result runs on the existing stage-parallel lowering with
output identical to the fused single-device run."""

import jax
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.parallel.autosplit import (AutoSplitError, auto_pipeline,
                                          balanced_partition)
from ziria_tpu.parallel.stages import lower_stage_parallel


def test_balanced_partition_minimizes_max():
    # [5,1,1,1,5] into 2: best max is 7 (cut after index 2 or 3)
    cuts = balanced_partition([5, 1, 1, 1, 5], 2)
    assert cuts in ([2], [3])
    # heavier head pulls the cut right
    assert balanced_partition([9, 1, 1, 1], 2) == [1]
    # every stage its own group
    assert balanced_partition([1, 2, 3], 3) == [1, 2]


def test_auto_pipeline_splits_and_matches_fused():
    stages = [z.zmap(lambda x, _k=k: x * 2 + _k, name=f"s{k}")
              for k in range(8)]
    prog = z.pipe(*stages)
    comp2 = auto_pipeline(prog, 8)
    assert len(ir.par_segments(comp2)) == 8
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("pp",))
    pp = lower_stage_parallel(
        comp2, mesh, in_item=jax.ShapeDtypeStruct((), np.float32),
        width=4)
    xs = np.arange(6 * pp.take, dtype=np.float32)
    ys = np.asarray(pp.run(xs.reshape(6, pp.take)))
    want = np.asarray(run_jit(prog, xs))
    np.testing.assert_allclose(
        ys.reshape(-1), want, rtol=1e-6)


def test_auto_pipeline_weights_by_rate():
    # an expanding stage doubles downstream reps, so the items-moved
    # cost is [2, 3, 4, 4, 4] for [pre, expand(1->2), a, b, c]: the
    # min-max 2-way cut is after THREE stages (9 | 8), not the naive
    # count split after two (5 | 12) — the partition must weight by
    # the SDF repetition vector
    import jax.numpy as jnp
    prog = z.pipe(
        z.zmap(lambda x: x + 1, name="pre"),
        z.zmap(lambda x: jnp.stack([x, -x]), in_arity=1, out_arity=2,
               name="expand"),
        z.zmap(lambda x: x * 2, name="a"),
        z.zmap(lambda x: x - 1, name="b"),
        z.zmap(lambda x: x ^ 3, name="c"))
    comp2 = auto_pipeline(prog, 2)
    segs = ir.par_segments(comp2)
    assert [len(ir.pipeline_stages(s)) for s in segs] == [3, 2]


def test_nested_parpipe_flattened_to_fixpoint():
    # a ParPipe nested UNDER a Pipe (parenthesized |>>>| in source)
    # must be flattened and re-decided, not survive as an opaque stage
    a = z.zmap(lambda x: x + 1, name="a")
    b = z.zmap(lambda x: x + 2, name="b")
    c = z.zmap(lambda x: x + 3, name="c")
    comp = ir.Pipe(a, ir.ParPipe(b, c))
    comp2 = auto_pipeline(comp, 2)
    assert len(ir.par_segments(comp2)) == 2
    assert sum(len(ir.pipeline_stages(s))
               for s in ir.par_segments(comp2)) == 3


def test_cost_uses_cardinality_for_repeat_stages():
    from ziria_tpu.parallel.autosplit import default_stage_cost
    # repeat { takes 64; emit sum } moves 65 items per firing
    rep = z.repeat(z.let("v", z.takes(64),
                         z.emit(lambda env: env["v"].sum())))
    assert default_stage_cost(rep, 1) == 65.0
    assert default_stage_cost(z.zmap(lambda x: x, name="m"), 3) == 6.0


def test_auto_pipeline_refuses_oversplit():
    prog = z.pipe(z.zmap(lambda x: x, name="a"),
                  z.zmap(lambda x: x, name="b"))
    with pytest.raises(AutoSplitError, match="cannot split"):
        auto_pipeline(prog, 3)


def test_cli_auto_pp(tmp_path):
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "chain.zir"
    src.write_text("""
      fun f1(x: int32) : int32 { return x * 2 }
      fun f2(x: int32) : int32 { return x + 7 }
      fun f3(x: int32) : int32 { return x ^ 21 }
      fun f4(x: int32) : int32 { return x - 3 }
      let comp main = read[int32] >>> map f1 >>> map f2 >>> map f3
                      >>> map f4 >>> write[int32]
    """)
    inf = tmp_path / "in.dbg"
    n = 4 * 2048                      # multiple of any macro chunk
    xs = np.arange(n, dtype=np.int32)
    inf.write_text(",".join(map(str, xs)))
    outs = {}
    for label, extra in (("plain", []), ("pp", ["--pp=4"])):
        outf = tmp_path / f"{label}.dbg"
        rc = cli_main([
            f"--src={src}", "--input=file", f"--input-file-name={inf}",
            "--input-file-mode=dbg", "--output=file",
            f"--output-file-name={outf}", "--output-file-mode=dbg",
            "--width=8",
        ] + extra)
        assert rc == 0
        outs[label] = outf.read_text()
    assert outs["plain"] == outs["pp"]


def test_auto_pipeline_measured_costs_shift_partition():
    # ROADMAP r4 §4: measured wall-time costs replace the items-moved
    # proxy. Four same-rate stages (proxy sees them equal) where one
    # does ~100x the arithmetic: the measured 2-way cut must isolate
    # the heavy stage's side, not split 2+2 blindly
    import ziria_tpu as z
    from ziria_tpu.parallel.autosplit import (_flatten, auto_pipeline,
                                              measured_stage_costs)

    def heavy(x):
        y = x
        for _ in range(120):
            y = (y * 1664525 + 1013904223) % 2147483647
        return y

    stages = [
        z.zmap(lambda x: x + 1, name="s0"),
        z.zmap(heavy, name="heavy"),
        z.zmap(lambda x: x * 3, name="s2"),
        z.zmap(lambda x: x - 2, name="s3"),
    ]
    prog = z.pipe(*stages)
    xs = np.arange(1 << 12, dtype=np.int32)
    costs = measured_stage_costs(_flatten(prog), xs, width=8)
    assert len(costs) == 4
    assert costs[1] == max(costs)

    out = auto_pipeline(prog, 2, sample=xs, width=8)
    from ziria_tpu.core import ir
    segs = ir.par_segments(out)
    assert len(segs) == 2
    # the heavy stage must NOT share a segment with both neighbors:
    # a 2-way cut lands at [s0 | heavy..] or [s0 heavy | ..]
    labels = [[s.label() for s in
               (_flatten(seg))] for seg in segs]
    heavy_seg = 0 if any("heavy" in l for l in labels[0]) else 1
    assert len(labels[heavy_seg]) <= 2


def test_cli_auto_pp_measured(tmp_path):
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "chain.zir"
    src.write_text("""
      fun f1(x: int32) : int32 { return x * 2 }
      fun f2(x: int32) : int32 { return x + 7 }
      fun f3(x: int32) : int32 { return x ^ 21 }
      fun f4(x: int32) : int32 { return x - 3 }
      let comp main = read[int32] >>> map f1 >>> map f2 >>> map f3
                      >>> map f4 >>> write[int32]
    """)
    inf = tmp_path / "in.dbg"
    xs = np.arange(4 * 2048, dtype=np.int32)
    inf.write_text(",".join(map(str, xs)))
    outs = {}
    for label, extra in (("plain", []),
                         ("pp", ["--pp=4", "--pp-costs=measured"])):
        outf = tmp_path / f"{label}.dbg"
        rc = cli_main([
            f"--src={src}", "--input=file", f"--input-file-name={inf}",
            "--input-file-mode=dbg", "--output=file",
            f"--output-file-name={outf}", "--output-file-mode=dbg",
            "--width=8",
        ] + extra)
        assert rc == 0
        outs[label] = outf.read_text()
    assert outs["plain"] == outs["pp"]
