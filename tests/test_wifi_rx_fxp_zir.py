"""The FIXED-POINT receiver as a program OF the framework
(examples/wifi_rx_fxp.zir + lib/wifi_rx_fxp_lib.zir, compiled under
--fxp-complex16).

The reference's receiver ran on int16 SORA bricks end to end; this
program expresses that discipline in the surface language — integer
detect/timing/CFO-NCO/channel-est/equalize/demap — and must decode the
same impaired captures the float in-language receiver does, under both
executors, with its FCS gate intact.
"""

import os

import numpy as np
import pytest

from ziria_tpu.backend import hybrid as H
from ziria_tpu.frontend import compile_file
from ziria_tpu.interp.interp import run
from ziria_tpu.phy import channel
from ziria_tpu.utils.bits import bytes_to_bits

SRC = os.path.join(os.path.dirname(__file__), "..", "examples",
                   "wifi_rx_fxp.zir")


def _prog():
    return compile_file(SRC, fxp_complex16=True)


def _capture(mbps, n_bytes, seed):
    psdu, cap = channel.impaired_capture(mbps, n_bytes, seed=seed,
                                         add_fcs=True)
    xs = [p for p in np.asarray(cap, np.int32)]
    want = np.asarray(bytes_to_bits(np.asarray(psdu, np.uint8)))
    return xs, want


@pytest.mark.parametrize("mbps,n_bytes", [(6, 40), (36, 70), (54, 90)])
def test_rx_fxp_zir_decodes_impaired_capture(mbps, n_bytes):
    xs, want = _capture(mbps, n_bytes, seed=300 + mbps)
    got = np.asarray(run(_prog().comp, xs).out_array(), np.uint8)
    np.testing.assert_array_equal(got, want)


def test_rx_fxp_zir_hybrid_matches_interp():
    prog = _prog()
    hyb = H.hybridize(prog.comp)
    for mbps, n_bytes, seed in ((24, 60, 320), (54, 90, 321)):
        xs, want = _capture(mbps, n_bytes, seed)
        gi = np.asarray(run(prog.comp, xs).out_array(), np.uint8)
        gh = np.asarray(run(hyb, xs).out_array(), np.uint8)
        np.testing.assert_array_equal(gi, want)
        np.testing.assert_array_equal(gh, want)


def test_rx_fxp_zir_deterministic_repeat():
    # integer chain: two runs of the same capture are bit-identical
    # (not just tolerance-equal)
    prog = _prog()
    xs, _ = _capture(48, 80, seed=330)
    a = np.asarray(run(prog.comp, xs).out_array(), np.uint8)
    b = np.asarray(run(prog.comp, xs).out_array(), np.uint8)
    np.testing.assert_array_equal(a, b)


def test_rx_fxp_zir_under_framebatch():
    """The fixed-point receiver is just another hybridized program to
    the frame batcher: N captures ride batched chunk steps and decode
    exactly as N sequential runs."""
    from ziria_tpu.backend.framebatch import StepBatcher, run_many
    prog = _prog()
    hyb = H.hybridize(prog.comp)
    caps = [_capture(m, nb, seed=350 + m)
            for m, nb in ((6, 30), (24, 60), (54, 90), (24, 45))]
    got = run_many(hyb, [xs for xs, _w in caps],
                   batcher=StepBatcher(len(caps)))
    for (xs, want), g in zip(caps, got):
        np.testing.assert_array_equal(
            np.asarray(g.out_array(), np.uint8), want)


def test_rx_fxp_zir_flag_matrix_ab_exact():
    """Flag-independence (the suite's metamorphic discipline, SURVEY
    §4): the fixed-point receiver's hybrid decode is bit-identical
    with the GF(2) loop compression and the lane vectorizer disabled."""
    xs, want = _capture(24, 60, seed=345)
    base = np.asarray(
        run(H.hybridize(_prog().comp), xs).out_array(), np.uint8)
    np.testing.assert_array_equal(base, want)
    for var in ("ZIRIA_NO_GF2_LOOPS", "ZIRIA_NO_VECTOR_LOOPS"):
        os.environ[var] = "1"
        try:
            got = np.asarray(
                run(H.hybridize(_prog().comp), xs).out_array(),
                np.uint8)
        finally:
            del os.environ[var]
        np.testing.assert_array_equal(got, base, err_msg=var)


@pytest.mark.parametrize("scale", [256.0, 8192.0, 24000.0, 30000.0])
def test_rx_fxp_zir_agc_amplitude_universal(scale):
    """The in-language power-of-two AGC normalizes ANY int16 capture
    into the Q schedule's envelope: the same frame decodes from 1/4x
    to rail-clipping amplitudes (at scale 30000 hundreds of samples
    saturate — the detector's pre-shifted products cannot wrap even
    at +-32768)."""
    psdu, cap = channel.impaired_capture(24, 40, seed=555, scale=scale,
                                         add_fcs=True)
    got = np.asarray(
        run(_prog().comp,
            [p for p in np.asarray(cap, np.int32)]).out_array(),
        np.uint8)
    np.testing.assert_array_equal(
        got, np.asarray(bytes_to_bits(np.asarray(psdu, np.uint8))))


def test_rx_fxp_zir_fcs_rejects_corruption():
    xs, _ = _capture(24, 60, seed=340)
    xs = [np.asarray(x) for x in xs]
    # corrupt the DATA region (pre=60 noise + 320 preamble + 80 SIGNAL)
    for k in range(520, 536):
        xs[k] = -xs[k]
    got = run(_prog().comp, xs).out_array()
    assert np.asarray(got).shape[0] == 0
