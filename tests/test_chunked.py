"""Chunked state machines (backend/chunked.py): stream-control loops
compiled to single device calls — the TPU counterpart of the reference
compiling per-sample take/emit loops into C state machines (SURVEY.md
§2.1 CgComp, §3.2 tick/process). The contract everywhere: output
bit-identical to the interpreter oracle, including EOF mid-loop.

(`Result.consumed` MAY legitimately exceed the oracle's when a chunked
loop reads ahead through a pipe — the same read-ahead the reference's
thread-separator queues perform; outputs and termination kind must
still match.)
"""

import numpy as np
import pytest

from ziria_tpu.backend import hybrid as H
from ziria_tpu.backend.chunked import _ChunkLoop, wrap_loops
from ziria_tpu.core import ir
from ziria_tpu.frontend import compile_source
from ziria_tpu.interp.interp import run


def _chunk_nodes(comp):
    out = []

    def walk(c):
        if isinstance(c, _ChunkLoop):
            out.append(c)
            walk(c.orig)
        for attr in ("first", "rest", "body", "then", "els", "up",
                     "down"):
            ch = getattr(c, attr, None)
            if isinstance(ch, ir.Comp):
                walk(ch)

    walk(comp)
    return out


def _assert_match(src, xs, min_chunks=1, check_consumed=True):
    prog = compile_source(src)
    want = run(prog.comp, list(xs))
    hyb = H.hybridize(prog.comp)
    assert len(_chunk_nodes(hyb)) >= min_chunks
    got = run(hyb, list(xs))
    np.testing.assert_array_equal(np.asarray(want.out_array()),
                                  np.asarray(got.out_array()))
    assert want.terminated_by == got.terminated_by
    if check_consumed:
        assert want.consumed == got.consumed
    # second run through the same wrapped object: caches must not
    # leak state across executions
    got2 = run(hyb, list(xs))
    np.testing.assert_array_equal(np.asarray(want.out_array()),
                                  np.asarray(got2.out_array()))
    return hyb


TAKE_BRANCH_SRC = """
let comp main = read[int32] >>> {
  var acc : arr[512] int32;
  var s : int32 := 0;
  times 256 {
    x <- take;
    do {
      if (x % 2 == 0) then { s := s + x } else { s := s + 1 };
      acc[s % 512] := x
    }
  };
  times 256 { emit acc[(s + 255) % 512]; do { s := s + 3 } }
} >>> write[int32]
"""


def test_for_take_branch_and_emit_loop():
    # data-dependent branch in a take loop + a separate emit loop, both
    # chunk-compiled; top-level (no pipe buffering => consumed matches)
    _assert_match(TAKE_BRANCH_SRC, np.arange(300, dtype=np.int32),
                  min_chunks=2)


def test_for_eof_midway():
    # input ends inside the take loop: outputs/termination match the
    # oracle exactly (interpreter tail path handles the final sliver)
    prog = compile_source(TAKE_BRANCH_SRC)
    hyb = H.hybridize(prog.comp)
    for n in (0, 1, 79, 255):
        xs = np.arange(n, dtype=np.int32)
        want = run(prog.comp, list(xs))
        got = run(hyb, list(xs))
        np.testing.assert_array_equal(np.asarray(want.out_array()),
                                      np.asarray(got.out_array()))
        assert want.terminated_by == got.terminated_by == "eof"


WHILE_SRC = """
let comp main = read[int32] >>> {
  var s : int32 := 0;
  var armed : bool := false;
  while (!armed) {
    x <- take;
    do {
      s := s + x * x - (s / 7);
      if (s % 1000 > 900) then { armed := true }
    }
  };
  emit s;
  (w : arr[20] int32) <- takes 20;
  do { for k in [0, 20] { s := s + w[k] } };
  emit s
} >>> write[int32]
"""


def test_while_detect_loop_pushback_visible():
    # the while over-pulls a window; the takes AFTER the loop must see
    # the pushed-back items — outputs prove the stream stayed intact
    prog = compile_source(WHILE_SRC)
    hyb = H.hybridize(prog.comp)
    assert len(_chunk_nodes(hyb)) == 1
    xs = (np.arange(1000, dtype=np.int32) * 7919) % 97
    want = run(prog.comp, list(xs))
    got = run(hyb, list(xs))
    np.testing.assert_array_equal(np.asarray(want.out_array()),
                                  np.asarray(got.out_array()))
    assert want.terminated_by == got.terminated_by == "computer"


def test_while_eof_before_arming():
    prog = compile_source(WHILE_SRC)
    hyb = H.hybridize(prog.comp)
    for n in (0, 3, 7):
        xs = np.zeros(n, np.int32)      # never arms
        want = run(prog.comp, list(xs))
        got = run(hyb, list(xs))
        np.testing.assert_array_equal(np.asarray(want.out_array()),
                                      np.asarray(got.out_array()))
        assert want.consumed == got.consumed == n
        assert want.terminated_by == got.terminated_by == "eof"


def test_loop_in_repeat_framed_stream():
    # a chunked loop under `repeat`: frame boundaries must survive the
    # window over-pull (pushback feeds the next repeat iteration)
    src = """
    let comp main = read[int32] >>> repeat {
      (h : arr[4] int32) <- takes 4;
      var s : int32 := 0;
      times 60 {
        x <- take;
        do { if (x > h[0]) then { s := s + x } else { s := s - x } }
      };
      emit s
    } >>> write[int32]
    """
    xs = (np.arange(64 * 5, dtype=np.int32) * 13) % 101
    _assert_match(src, xs, min_chunks=1)


def test_nested_loop_with_lead_buffer():
    # the wifi symbol-gather shape: inner per-sample loop choosing
    # between a preloaded buffer and the live stream, under an outer
    # symbol loop — both staged into ONE machine
    src = """
    let comp main = read[int32] >>> {
      var lead : arr[48] int32;
      var g : int32 := 0;
      var acc : int32 := 0;
      do { for i in [0, 48] { lead[i] := 1000 + i } };
      times 8 {
        times 40 {
          var v : int32 := 0;
          if (g < 48) then { do { v := lead[g] } }
          else { x <- take; do { v := x * 2 } };
          do { g := g + 1; acc := acc + v }
        };
        emit acc
      }
    } >>> write[int32]
    """
    xs = np.arange(400, dtype=np.int32)
    _assert_match(src, xs, min_chunks=1, check_consumed=False)


def test_effectful_loop_not_wrapped():
    src = """
    let comp main = read[int32] >>> {
      var s : int32 := 0;
      times 300 { x <- take; do { s := s + x; println s } };
      emit s
    } >>> write[int32]
    """
    prog = compile_source(src)
    hyb = H.hybridize(prog.comp)
    assert len(_chunk_nodes(hyb)) == 0


def test_tiny_loop_falls_back_to_interp():
    # below MIN_ITEMS_FOR the wrapper delegates (gate is at runtime —
    # the node exists but the run matches and stays cheap)
    src = """
    let comp main = read[int32] >>> {
      var s : int32 := 0;
      times 4 { x <- take; do { s := s + x } };
      emit s
    } >>> write[int32]
    """
    _assert_match(src, np.arange(10, dtype=np.int32), min_chunks=0)


def test_wrap_decisions_dumped():
    lines = []
    H.hybridize(compile_source(TAKE_BRANCH_SRC).comp, dump=lines.append)
    assert any("chunked For" in l for l in lines)


def test_value_select_keeps_big_buffers_unswapped():
    # the staged-if value-select peephole (frontend/eval.py): both arms
    # write ONE element of a >4096-entry buffer through the same index;
    # jit result must equal the interpreter exactly
    from ziria_tpu.backend.execute import run_jit
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[16] int32) <- takes 16;
      var dep : arr[8192] int32;
      var s : int32 := 0;
      do {
        for t in [0, 512] {
          var keep : int32 := 1;
          if (t % 4 == 3) then { keep := 0 };
          if (keep == 1) then { dep[t] := v[t % 16] * t; s := s + 1 }
          else { dep[t] := 0 - 7 }
        }
      };
      emit dep[100] + dep[103] + s
    } >>> write[int32]
    """
    prog = compile_source(src)
    xs = (np.arange(64, dtype=np.int32) * 31) % 257
    want = run(prog.comp, list(xs)).out_array()
    got = np.asarray(run_jit(prog.comp, xs))
    np.testing.assert_array_equal(np.asarray(want), got)


def test_pipe_value_survives_bulk_pull_eof():
    # code-review r3: Source.pull_block used to swallow UpstreamDone
    # and its value; the re-pull of the exhausted upstream generator
    # then produced UpstreamDone(None) — a Pipe whose downstream hits
    # EOF via a bulk `takes` lost the upstream computer's return value
    import ziria_tpu as z

    up = z.seq(z.emits(np.arange(3, dtype=np.int32), 3), z.ret(42))
    down = z.let("w", z.takes(5), z.emit1(lambda env: env["w"][0]))
    r = run(ir.Pipe(up, down), [])
    assert r.value == 42
    assert r.terminated_by == "computer"


VAR_TAKE_TAIL_SRC = """
let comp main = read[int32] >>> {
  var s : int32 := 0;
  times 256 {
    x <- take;
    do { s := s + 1 };
    if (x < 0) then { y <- take; do { s := s + y } }
  };
  emit s * s
} >>> write[int32]
"""


def test_interp_tail_ref_update_survives_final_writeback():
    # advisor r3 (high): worst-case take bound 2 but actual take 1 per
    # iteration. Fed exactly 256 items, the LAST iteration finds one
    # buffered item < take_bound and runs on the interpreter tail; its
    # direct-in-env ref update (s: 255 -> 256) must not be clobbered by
    # the final write_back of stale pre-tail device values
    xs = np.arange(256, dtype=np.int32)   # all >= 0: branch never takes
    _assert_match(VAR_TAKE_TAIL_SRC, xs, min_chunks=1,
                  check_consumed=False)


def test_interp_tail_then_more_chunk_steps():
    # tail iterations interleaved with later chunk steps: a slow drip
    # source shape — here EOF lengths that force several tail entries
    prog = compile_source(VAR_TAKE_TAIL_SRC)
    hyb = H.hybridize(prog.comp)
    for n in (255, 257, 300):
        xs = np.arange(n, dtype=np.int32) - 5   # a few negatives: some
        want = run(prog.comp, list(xs))         # iterations take 2
        got = run(hyb, list(xs))
        np.testing.assert_array_equal(np.asarray(want.out_array()),
                                      np.asarray(got.out_array()))
        assert want.terminated_by == got.terminated_by


EMIT_WHILE_SRC = """
let comp main = read[int32] >>> {
  var s : int32 := 0;
  var h : int32 := 1;
  var armed : bool := false;
  while (!armed) {
    x <- take;
    do {
      s := s + x * h;
      h := (h * 31 + 7) % 101;
      if (s % 977 > 900) then { armed := true }
    };
    emit s;
    emit h
  };
  emit 0 - s
} >>> write[int32]
"""


def test_emitting_while_chunked():
    # VERDICT r3 next #7: a detect-then-emit While runs as a chunked
    # machine — emissions bounded per chunk by the iteration cap
    xs = (np.arange(3000, dtype=np.int32) * 13) % 37
    hyb = _assert_match(EMIT_WHILE_SRC, xs, min_chunks=1,
                        check_consumed=False)
    # the machine actually compiled and ran (not a silent fallback)
    assert all(n._fns for n in _chunk_nodes(hyb))


def test_emitting_while_eof_midway():
    prog = compile_source(EMIT_WHILE_SRC)
    hyb = H.hybridize(prog.comp)
    assert len(_chunk_nodes(hyb)) >= 1
    for n in (0, 1, 5, 63):
        xs = np.ones(n, np.int32)     # may never arm: EOF inside loop
        want = run(prog.comp, list(xs))
        got = run(hyb, list(xs))
        np.testing.assert_array_equal(np.asarray(want.out_array()),
                                      np.asarray(got.out_array()))
        assert want.terminated_by == got.terminated_by


def test_emitting_while_small_iter_cap(monkeypatch):
    # force a tiny output budget so one execution needs MANY chunk
    # steps — the cap/flush/re-enter cycle must stay exact
    from ziria_tpu.backend import chunked as CH
    monkeypatch.setattr(CH, "WHILE_OUT_ITEMS", 32)
    xs = (np.arange(3000, dtype=np.int32) * 13) % 37
    _assert_match(EMIT_WHILE_SRC, xs, min_chunks=1,
                  check_consumed=False)


def test_emitting_while_fuzz_oracle():
    prog = compile_source(EMIT_WHILE_SRC)
    hyb = H.hybridize(prog.comp)
    rng = np.random.default_rng(17)
    for _ in range(5):
        n = int(rng.integers(0, 4000))
        xs = rng.integers(0, 50, n).astype(np.int32)
        want = run(prog.comp, list(xs))
        got = run(hyb, list(xs))
        np.testing.assert_array_equal(np.asarray(want.out_array()),
                                      np.asarray(got.out_array()))
        assert want.terminated_by == got.terminated_by
