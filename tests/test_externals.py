"""Stdlib externals registry (the reference's lib/ v_* corpus,
SURVEY.md §2.3 — VERDICT r1 #8): numpy path == jnp path, and the
bit/byte helpers invert each other."""

import jax.numpy as jnp
import numpy as np
import pytest

from ziria_tpu.frontend.externals import EXTERNALS

RNG = np.random.default_rng(7)


@pytest.mark.parametrize("name", ["v_add", "v_sub", "v_mul"])
def test_v_binops_both_paths(name):
    a = RNG.standard_normal(32).astype(np.float32)
    b = RNG.standard_normal(32).astype(np.float32)
    fn = EXTERNALS[name]
    got_np = fn(a, b)
    assert isinstance(got_np, np.ndarray)
    got_j = np.asarray(fn(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got_np, got_j, rtol=1e-6)


def test_v_conj_mul_and_correlate():
    x = (RNG.standard_normal(64) + 1j * RNG.standard_normal(64)) \
        .astype(np.complex64)
    r = x[:16]
    cm = EXTERNALS["v_conj_mul"](x[:16], r)
    np.testing.assert_allclose(cm, np.abs(r) ** 2, atol=1e-5)
    corr = EXTERNALS["v_correlate"](x, r)
    assert corr.shape[0] == 64 - 16 + 1
    want0 = (x[:16] * np.conj(r)).sum()
    np.testing.assert_allclose(corr[0], want0, rtol=1e-5)


def test_v_shifts_and_downsample():
    x = np.array([-64, -8, 8, 1024], np.int32)
    np.testing.assert_array_equal(
        EXTERNALS["v_shift_right"](x, 3), x >> 3)
    np.testing.assert_array_equal(
        EXTERNALS["v_shift_left"](x, 2), x << 2)
    y = np.arange(10)
    np.testing.assert_array_equal(EXTERNALS["v_downsample"](y, 2),
                                  y[::2])


def test_v_sum_window():
    x = RNG.standard_normal(50).astype(np.float32)
    got = EXTERNALS["v_sum_window"](x, 8)
    want = np.array([x[k:k + 8].sum() for k in range(43)], np.float32)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_crc32_both_paths_agree():
    bits = RNG.integers(0, 2, 128).astype(np.uint8)
    got_np = EXTERNALS["crc32"](bits)
    got_j = np.asarray(EXTERNALS["crc32"](jnp.asarray(bits)))
    np.testing.assert_array_equal(got_np, got_j)


def test_bits_bytes_roundtrip():
    bits = RNG.integers(0, 2, 64).astype(np.uint8)
    by = EXTERNALS["bits_to_int8"](bits)
    assert by.dtype == np.int8 and by.shape == (8,)
    back = EXTERNALS["int8_to_bits"](by)
    np.testing.assert_array_equal(back, bits)
    by_j = np.asarray(EXTERNALS["bits_to_int8"](jnp.asarray(bits)))
    np.testing.assert_array_equal(by_j, by)
