"""Dead-backend fast-fail in the CLI driver (VERDICT r4 weak #8).

The axon TPU plugin, when its tunnel is down, hangs backend init for
minutes and overrides the JAX_PLATFORMS env var. The CLI therefore
health-checks the default backend in a bounded subprocess and fails in
seconds with an actionable message. These tests drive the probe with
injected commands (a sleeper for the hang, /bin/true-alikes for
health) so no real backend is needed.
"""

import sys
import time
import types

from ziria_tpu.runtime import cli


def _args(platform=None):
    return types.SimpleNamespace(platform=platform)


def test_probe_detects_hang_quickly():
    t0 = time.perf_counter()
    failed = cli._backend_probe_failed(
        0.5, probe_argv=[sys.executable, "-c", "import time; time.sleep(60)"])
    assert failed
    assert time.perf_counter() - t0 < 5.0


def test_probe_passes_healthy_backend():
    assert not cli._backend_probe_failed(
        10.0, probe_argv=[sys.executable, "-c", "pass"])


def test_probe_detects_crash():
    assert cli._backend_probe_failed(
        10.0, probe_argv=[sys.executable, "-c", "raise SystemExit(1)"])


def test_pinned_platform_skips_probe(monkeypatch):
    # a pinned platform goes through jax.config and cannot hang — the
    # probe (and its subprocess cost) must be skipped entirely
    monkeypatch.delenv("ZIRIA_PLATFORM", raising=False)
    called = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: called.append(1) or True)
    assert cli._fastfail_dead_backend(_args(platform="cpu")) is None
    assert not called


def test_env_zero_disables_probe(monkeypatch):
    monkeypatch.delenv("ZIRIA_PLATFORM", raising=False)
    monkeypatch.setenv("ZIRIA_BACKEND_PROBE_TIMEOUT", "0")
    called = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: called.append(1) or True)
    assert cli._fastfail_dead_backend(_args()) is None
    assert not called


def _simulate_axon_box(monkeypatch, tmp_path):
    """Make the fast-fail see the axon-box situation regardless of the
    test environment: env routes to a tunnelled plugin, no in-process
    pin, no busy flag held."""
    monkeypatch.delenv("ZIRIA_PLATFORM", raising=False)
    monkeypatch.delenv("ZIRIA_BACKEND_PROBE_TIMEOUT", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setattr(cli, "_jax_platforms_pinned", lambda: False)
    monkeypatch.setattr(cli, "TPU_BUSY_FLAG",
                        str(tmp_path / "no_such_flag"))
    # a success cached by an earlier test must not leak in
    monkeypatch.setattr(cli, "_probe_ok_t", 0.0)


def test_dead_backend_returns_rc2(monkeypatch, capsys, tmp_path):
    _simulate_axon_box(monkeypatch, tmp_path)
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: True)
    assert cli._fastfail_dead_backend(_args()) == 2
    assert "--platform=cpu" in capsys.readouterr().err


def test_healthy_backend_continues(monkeypatch, tmp_path):
    _simulate_axon_box(monkeypatch, tmp_path)
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: False)
    assert cli._fastfail_dead_backend(_args()) is None


def test_busy_flag_reported_without_probing(monkeypatch, capsys,
                                            tmp_path):
    # a fresh /tmp/tpu_busy analogue means the backend is HELD, not
    # dead: the CLI must say so and must NOT attach a second axon
    # client (review finding: two concurrent clients both hang)
    _simulate_axon_box(monkeypatch, tmp_path)
    flag = tmp_path / "busy"
    flag.write_text("watcher pid 123\n")
    monkeypatch.setattr(cli, "TPU_BUSY_FLAG", str(flag))
    probed = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: probed.append(1) or False)
    assert cli._fastfail_dead_backend(_args()) == 2
    assert "held by another client" in capsys.readouterr().err
    assert not probed


def test_cpu_env_routing_skips_probe(monkeypatch, tmp_path):
    # an ordinary machine (no axon routing) must not pay the probe
    _simulate_axon_box(monkeypatch, tmp_path)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    probed = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: probed.append(1) or True)
    assert cli._fastfail_dead_backend(_args()) is None
    assert not probed
    monkeypatch.delenv("JAX_PLATFORMS")
    assert cli._fastfail_dead_backend(_args()) is None
    assert not probed


def test_inprocess_pin_skips_probe(monkeypatch):
    # under the test conftest jax_platforms IS pinned — the probe must
    # not run (this is the embedder/test-suite path)
    monkeypatch.delenv("ZIRIA_PLATFORM", raising=False)
    monkeypatch.delenv("ZIRIA_BACKEND_PROBE_TIMEOUT", raising=False)
    called = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: called.append(1) or True)
    assert cli._fastfail_dead_backend(_args()) is None
    assert not called


def test_probe_holds_busy_flag_and_releases(monkeypatch, tmp_path):
    # TOCTOU fix (ADVICE r5 #2): the probe runs UNDER an O_EXCL claim
    # of the busy flag, so a watcher starting mid-probe waits instead
    # of attaching a second axon client; the claim is released after
    _simulate_axon_box(monkeypatch, tmp_path)
    flag = tmp_path / "busy"
    monkeypatch.setattr(cli, "TPU_BUSY_FLAG", str(flag))
    seen = []
    monkeypatch.setattr(
        cli, "_backend_probe_failed",
        lambda *a, **k: seen.append(
            flag.exists() and "cli probe" in flag.read_text()) or False)
    assert cli._fastfail_dead_backend(_args()) is None
    assert seen == [True]
    assert not flag.exists()


def test_stale_flag_taken_over_for_probe(monkeypatch, tmp_path):
    # a leaked flag (older than BUSY_STALE_S) must not block forever:
    # the claim takes it over, probes, and releases
    import os
    _simulate_axon_box(monkeypatch, tmp_path)
    flag = tmp_path / "busy"
    flag.write_text("dead holder\n")
    old = time.time() - cli.BUSY_STALE_S - 60
    os.utime(flag, (old, old))
    monkeypatch.setattr(cli, "TPU_BUSY_FLAG", str(flag))
    probed = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: probed.append(1) or False)
    assert cli._fastfail_dead_backend(_args()) is None
    assert probed and not flag.exists()


def test_successful_probe_cached(monkeypatch, tmp_path):
    # the healthy path pays ONE probe subprocess, not one per
    # invocation: a recent success short-circuits the next call
    _simulate_axon_box(monkeypatch, tmp_path)
    probed = []
    monkeypatch.setattr(cli, "_backend_probe_failed",
                        lambda *a, **k: probed.append(1) or False)
    assert cli._fastfail_dead_backend(_args()) is None
    assert cli._fastfail_dead_backend(_args()) is None
    assert len(probed) == 1


def test_failed_probe_not_cached(monkeypatch, tmp_path):
    # only SUCCESS is cached: a dead tunnel is re-probed next time
    _simulate_axon_box(monkeypatch, tmp_path)
    results = [True, False]
    probed = []
    monkeypatch.setattr(
        cli, "_backend_probe_failed",
        lambda *a, **k: probed.append(1) or results[len(probed) - 1])
    assert cli._fastfail_dead_backend(_args()) == 2
    assert cli._fastfail_dead_backend(_args()) is None
    assert len(probed) == 2


def test_claim_busy_flag_lost_race(monkeypatch, tmp_path):
    # a fresh flag appearing between the staleness check and the claim
    # is a live client: report held (None from _claim_busy_flag)
    flag = tmp_path / "busy"
    flag.write_text("watcher pid 9\n")
    monkeypatch.setattr(cli, "TPU_BUSY_FLAG", str(flag))
    assert cli._claim_busy_flag() is None
    assert flag.read_text() == "watcher pid 9\n"   # untouched
