"""End-to-end frontend tests: parse → elaborate → run on BOTH backends.

The flag-matrix discipline of the reference test suite (SURVEY.md §4):
every program must produce identical output under the interpreter oracle
and the fused jit backend, with and without the fold pass.
"""

import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.core import ir
from ziria_tpu.core.localize import localize
from ziria_tpu.core.opt import fold
from ziria_tpu.core.types import typecheck
from ziria_tpu.frontend import ElabError, ZiriaRuntimeError, compile_source
from ziria_tpu.interp.interp import run


def both_backends(prog, xs, max_out=None):
    """Run under interp and jit (fold on/off); assert all agree."""
    res = run(prog.comp, list(np.asarray(xs)), max_out=max_out)
    ref = res.out_array()
    outs = {"interp": ref}
    outs["jit"] = run_jit(prog.comp, xs)
    outs["jit+fold"] = run_jit(prog.comp, xs, optimize=True)
    for name, got in outs.items():
        got = np.asarray(got)
        assert got.shape[0] == ref.shape[0], \
            f"{name}: {got.shape} vs interp {ref.shape}"
        np.testing.assert_allclose(
            np.asarray(got, np.float64), np.asarray(ref, np.float64),
            rtol=1e-5, atol=1e-5, err_msg=name)
    return ref


# ------------------------------------------------------------------ basics

def test_map_fun_pipeline():
    prog = compile_source("""
      fun incr(x: int32) : int32 { return x + 1 }
      let comp main = read[int32] >>> map incr >>> write[int32]
    """)
    assert prog.in_ty == "int32" and prog.out_ty == "int32"
    xs = np.arange(32, dtype=np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, xs + 1)


def test_repeat_take_emit_expression():
    prog = compile_source("""
      let comp main = read[int32] >>> repeat { x <- take; emit x * x }
                      >>> write[int32]
    """)
    xs = np.arange(16, dtype=np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, xs * xs)


def test_takes_emits_block():
    prog = compile_source("""
      let comp main = read[int32] >>>
        repeat { (x: arr[4] int32) <- takes 4; emits x[0,2]; emit x[3] }
        >>> write[int32]
    """)
    xs = np.arange(16, dtype=np.int32)
    out = both_backends(prog, xs)
    want = np.concatenate([[4 * k, 4 * k + 1, 4 * k + 3]
                           for k in range(4)])
    np.testing.assert_array_equal(out, want)


def test_stateful_scrambler_localizes_to_mapaccum():
    """The ASPLOS scrambler shape: var + repeat + do-block → MapAccum."""
    prog = compile_source("""
      let comp scrambler = {
        var st : arr[7] bit := {'1,'1,'1,'1,'1,'1,'1};
        var tmp : bit := '0;
        repeat {
          x <- take;
          do { tmp := st[3] ^ st[6];
               st[1, 6] := st[0, 6];
               st[0] := tmp };
          emit x ^ tmp
        }
      }
      let comp main = read[bit] >>> scrambler >>> write[bit]
    """)
    # localization must have produced a MapAccum (jit-able state)
    assert isinstance(prog.comp, ir.MapAccum), type(prog.comp).__name__
    rng = np.random.default_rng(0)
    xs = rng.integers(0, 2, 128).astype(np.uint8)
    out = both_backends(prog, xs)
    # oracle: the same LFSR in numpy (x^{7}+x^{4}+1, MSB-first shift-down)
    st = np.ones(7, np.uint8)
    want = np.zeros(128, np.uint8)
    for k, x in enumerate(xs):
        tmp = st[3] ^ st[6]
        st[1:7] = st[0:6]
        st[0] = tmp
        want[k] = x ^ tmp
    np.testing.assert_array_equal(out.astype(np.uint8), want)


def test_wifi_scrambler_matches_ops_oracle():
    """802.11 scrambler written in surface syntax == ops/scramble.py."""
    from ziria_tpu.ops.scramble import np_lfsr_sequence_127
    prog = compile_source("""
      let comp main = read[bit] >>> {
        var st : arr[7] bit := {'1,'0,'1,'1,'1,'0,'1};
        repeat {
          x <- take;
          var fb : bit := '0;
          do { fb := st[3] ^ st[0];
               st[0, 6] := st[1, 6];
               st[6] := fb };
          emit x ^ fb
        }
      } >>> write[bit]
    """)
    seed = np.array([1, 0, 1, 1, 1, 0, 1], np.uint8)
    seq = np_lfsr_sequence_127(seed)
    xs = np.zeros(254, np.uint8)   # scrambling zeros yields the sequence
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out.astype(np.uint8),
                                  np.resize(seq, 254))


def test_fir_with_state():
    prog = compile_source("""
      let comp main = read[int32] >>> {
        var delay : arr[4] int32 := {0, 0, 0, 0};
        repeat {
          x <- take;
          do { delay[1, 3] := delay[0, 3]; delay[0] := x };
          emit delay[0] + delay[1] + delay[2] + delay[3]
        }
      } >>> write[int32]
    """)
    xs = np.arange(1, 33, dtype=np.int32)
    out = both_backends(prog, xs)
    want = np.convolve(xs, np.ones(4, np.int64))[:32].astype(np.int64)
    np.testing.assert_array_equal(out.astype(np.int64), want)


# ------------------------------------------------------------ control flow

def test_static_for_loop_unrolled():
    prog = compile_source("""
      let comp main = read[int32] >>>
        repeat { x <- take; for i in [1, 3] { emit x * i } }
        >>> write[int32]
    """)
    xs = np.array([10, 20], np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, [10, 20, 30, 20, 40, 60])


def test_dynamic_if_in_do_block_stages():
    """Data-dependent statement-if must stage into where() under jit."""
    prog = compile_source("""
      let comp main = read[int32] >>> {
        var acc : int32 := 0;
        repeat {
          x <- take;
          do { if x > 0 then { acc := acc + x } else { acc := acc - 1 } };
          emit acc
        }
      } >>> write[int32]
    """)
    xs = np.array([5, -2, 3, 0, 7], np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, [5, 4, 7, 6, 13])


def test_expression_cond_dynamic():
    prog = compile_source("""
      let comp main = read[int32] >>>
        repeat { x <- take; emit (if x % 2 == 0 then x / 2 else 3 * x + 1) }
        >>> write[int32]
    """)
    xs = np.array([6, 7, 8, 9], np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, [3, 22, 4, 28])


def test_comp_if_static_folds():
    prog = compile_source("""
      let rate = 2
      fun dbl(x: int32) : int32 { return 2 * x }
      fun neg(x: int32) : int32 { return -x }
      let comp main = read[int32] >>>
        (if rate > 1 then map dbl else map neg) >>> write[int32]
    """)
    xs = np.arange(8, dtype=np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, 2 * xs)


def test_while_computer_interp():
    """Dynamic while runs on the interpreter (jit refuses, by design)."""
    prog = compile_source("""
      let comp main = read[int32] >>> {
        var n : int32 := 3;
        while (n > 0) { x <- take; do { n := n - 1 }; emit x * 10 }
      } >>> write[int32]
    """)
    res = run(prog.comp, list(np.arange(8, dtype=np.int32)))
    np.testing.assert_array_equal(res.out_array(), [0, 10, 20])


def test_until_loop_interp():
    prog = compile_source("""
      let comp main = read[int32] >>> {
        var s : int32 := 0;
        until (s >= 10) { x <- take; do { s := s + x }; emit s }
      } >>> write[int32]
    """)
    res = run(prog.comp, list(np.arange(1, 9, dtype=np.int32)))
    np.testing.assert_array_equal(res.out_array(), [1, 3, 6, 10])


# ------------------------------------------------------------- comp funs

def test_comp_fun_static_arg_inlines():
    prog = compile_source("""
      fun comp scale(k: int32) { repeat { x <- take; emit x * k } }
      let comp main = read[int32] >>> scale(3) >>> scale(2)
                      >>> write[int32]
    """)
    xs = np.arange(8, dtype=np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, 6 * xs)


def test_comp_fun_runtime_arg():
    """A comp-fun arg depending on a bound value threads via the env."""
    prog = compile_source("""
      fun comp add_k(k: int32) { repeat { x <- take; emit x + k } }
      let comp main = read[int32] >>>
        { h <- take; add_k(h) } >>> write[int32]
    """)
    xs = np.array([100, 1, 2, 3], np.int32)
    res = run(prog.comp, list(xs))
    np.testing.assert_array_equal(res.out_array(), [101, 102, 103])


def test_let_comp_local():
    prog = compile_source("""
      let comp main = read[int32] >>> {
        let comp dbl = repeat { x <- take; emit 2 * x };
        dbl
      } >>> write[int32]
    """)
    xs = np.arange(4, dtype=np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, 2 * xs)


def test_top_level_comp_reference():
    prog = compile_source("""
      let comp stage1 = repeat { x <- take; emit x + 1 }
      let comp main = read[int32] >>> stage1 >>> stage1 >>> write[int32]
    """)
    xs = np.arange(4, dtype=np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, xs + 2)


# ------------------------------------------------------------- ext + types

def test_ext_fft_roundtrip():
    prog = compile_source("""
      ext fun v_fft(x: arr[64] complex16) : arr[64] complex16
      ext fun v_ifft(x: arr[64] complex16) : arr[64] complex16
      fun comp spectral() {
        repeat { (s: arr[64] complex16) <- takes 64;
                 emits v_ifft(v_fft(s)) }
      }
      let comp main = read[complex16] >>> spectral() >>> write[complex16]
    """)
    assert prog.in_ty == "complex16" and prog.out_ty == "complex16"
    rng = np.random.default_rng(1)
    xs = rng.integers(-100, 100, (128, 2)).astype(np.int16)
    out = both_backends(prog, xs)
    np.testing.assert_allclose(out, xs, atol=1.0)  # int16 round-trip


def test_double_and_cast():
    prog = compile_source("""
      fun scale(x: int16) : double { return double(x) * 0.5 }
      let comp main = read[int16] >>> map scale >>> write[double]
    """)
    assert prog.out_ty == "float32"
    xs = np.arange(-4, 4, dtype=np.int16)
    out = both_backends(prog, xs)
    np.testing.assert_allclose(out, xs * 0.5)


def test_int16_wraparound():
    prog = compile_source("""
      fun bump(x: int16) : int16 { return x + 1 }
      let comp main = read[int16] >>> map bump >>> write[int16]
    """)
    xs = np.array([32767, -32768, 0], np.int16)
    res = run(prog.comp, list(xs))
    np.testing.assert_array_equal(res.out_array().astype(np.int16),
                                  [-32768, -32767, 1])


def test_struct_roundtrip():
    prog = compile_source("""
      struct Pkt = { hi: int32; lo: int32 }
      fun pack(x: int32) : int32 {
        var p : Pkt := Pkt { hi = x / 256, lo = x % 256 };
        return p.hi * 256 + p.lo
      }
      let comp main = read[int32] >>> map pack >>> write[int32]
    """)
    xs = np.array([0, 511, 70000], np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, xs)


def test_typecheck_elaborated_ir():
    prog = compile_source("""
      let comp main = read[int32] >>> repeat { x <- take; emit x }
                      >>> write[int32]
    """)
    t = typecheck(prog.comp)
    assert t.kind() == "transformer"


# ------------------------------------------------------------------ errors

def test_unbound_variable_reports_loc():
    with pytest.raises(ElabError, match="unbound"):
        compile_source("let comp main = read[bit] >>> "
                       "repeat { x <- take; emit y } >>> write[bit]")


def test_unknown_ext():
    with pytest.raises(ElabError, match="registry"):
        compile_source("ext fun warp_core(x: int32) : int32\n"
                       "let comp main = read[int32] >>> map warp_core "
                       ">>> write[int32]")


def test_emits_unknown_length():
    with pytest.raises(ElabError, match="emits"):
        compile_source("""
          let comp main = read[int32] >>>
            repeat { x <- take; emits x } >>> write[int32]
        """)


def test_runtime_error_has_position():
    prog = compile_source("""
      fun f(x: int32) : int32 { error "boom"; return x }
      let comp main = read[int32] >>> map f >>> write[int32]
    """)
    with pytest.raises(ZiriaRuntimeError, match="boom"):
        run(prog.comp, [np.int32(1)])


def test_misplaced_read():
    with pytest.raises(ElabError, match="pipeline ends"):
        compile_source("let comp main = repeat { x <- take; emit x } "
                       ">>> read[bit] >>> write[bit]")


# ----------------------------------------------------- review regressions

def test_runtime_bind_shadows_static_global():
    """A take-bound name shadowing a top-level let must NOT constant-fold
    to the global's value."""
    prog = compile_source("""
      let k = 3
      let comp main = read[int32] >>> repeat { k <- take; emit k }
                      >>> write[int32]
    """)
    xs = np.array([10, 20, 30], np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, xs)


def test_local_array_with_named_length_assign():
    prog = compile_source("""
      let N = 4
      fun f(x: int32) : int32 {
        var acc : arr[N] int32;
        acc[0] := x;
        return acc[0] + acc[3]
      }
      let comp main = read[int32] >>> map f >>> write[int32]
    """)
    xs = np.array([7, 9], np.int32)
    out = both_backends(prog, xs)
    np.testing.assert_array_equal(out, xs)


def test_times_with_named_count_parses():
    prog = compile_source("""
      let n = 2
      let comp main = read[int32] >>>
        { times n { x <- take; emit x + 1 }; emit 99 } >>> write[int32]
    """)
    res = run(prog.comp, list(np.array([5, 6], np.int32)))
    np.testing.assert_array_equal(res.out_array(), [6, 7, 99])


def test_bad_hex_literal_is_lex_error():
    from ziria_tpu.frontend import LexError
    with pytest.raises(LexError, match="hex"):
        compile_source("let x = 0x\nlet comp main = read[bit] >>> "
                       "repeat { b <- take; emit b } >>> write[bit]")


def test_comp_fun_arg_not_shadowed_by_earlier_param():
    """f(u, a) where the caller's `a` collides with f's first param: the
    second argument must see the CALLER's a, not the fresh binding."""
    prog = compile_source("""
      fun comp f(a: int32, b: int32) { x <- take; emit x + b }
      let comp main = read[int32] >>>
        { a <- take; u <- take; f(u, a) } >>> write[int32]
    """)
    res = run(prog.comp, list(np.array([10, 99, 7], np.int32)))
    np.testing.assert_array_equal(res.out_array(), [17])


def test_impure_let_evaluates_once_at_runtime(capsys):
    prog = compile_source("""
      fun noisy() : int32 { println "SIDE EFFECT"; return 5 }
      let comp main = read[int32] >>>
        { let k = noisy(); repeat { x <- take; emit x + k } }
        >>> write[int32]
    """)
    assert capsys.readouterr().out.count("SIDE EFFECT") == 0
    res = run(prog.comp, list(np.array([1, 2], np.int32)))
    np.testing.assert_array_equal(res.out_array(), [6, 7])
    assert capsys.readouterr().out.count("SIDE EFFECT") == 1


def test_negative_index_rejected():
    # a statically-known negative index is now a *compile-time* error
    # (round-2 typechecker); the dynamic variant below still errors at
    # runtime
    from ziria_tpu.frontend import ZiriaTypeError
    with pytest.raises(ZiriaTypeError, match="out of bounds"):
        compile_source("""
          fun f(x: int32) : int32 {
            var a : arr[4] int32 := {10, 20, 30, 40};
            return a[0 - 1]
          }
          let comp main = read[int32] >>> map f >>> write[int32]
        """)


def test_negative_dynamic_index_rejected_at_runtime():
    prog = compile_source("""
      fun f(x: int32) : int32 {
        var a : arr[4] int32 := {10, 20, 30, 40};
        return a[x - 1]
      }
      let comp main = read[int32] >>> map f >>> write[int32]
    """)
    with pytest.raises(ZiriaRuntimeError, match="out of bounds"):
        run(prog.comp, [np.int32(0)])


# ------------------------------------------------- ADVICE r1 regressions


def test_narrow_int_promotion_matches_c():
    """int16 operands promote to int32 before arithmetic (C integer
    promotion): 300*300 is 90000 mid-expression on EVERY path, and
    narrows to 24464 only when assigned back to an int16 slot."""
    prog = compile_source("""
      fun f(x: int16) : int32 {
        var wide : int32;
        var narrow : int16;
        wide := x * x;
        narrow := x * x;
        return wide - narrow
      }
      let comp main = read[int16] >>> map f >>> write[int32]
    """)
    out = both_backends(prog, np.array([300], np.int16))
    # 90000 - 24464 = 65536 on both paths
    np.testing.assert_array_equal(out, [65536])


def test_expression_statement_with_operator():
    """`f(x) + g(y);` is a legal (if useless) expression statement."""
    prog = compile_source("""
      fun g(y: int32) : int32 { return y + 1 }
      fun f(x: int32) : int32 {
        g(x) + g(x);
        return x
      }
      let comp main = read[int32] >>> map f >>> write[int32]
    """)
    out = both_backends(prog, np.array([5], np.int32))
    np.testing.assert_array_equal(out, [5])


def test_staged_if_struct_cell_merges_fieldwise():
    """Assigning a struct variable inside a data-dependent if merges
    per field with jnp.where (field assignment is copy-on-write, so
    whole-dict replacement is the common case — ADVICE r1 follow-up)."""
    import jax.numpy as jnp

    from ziria_tpu.frontend import eval as E
    from ziria_tpu.frontend.parser import Parser

    src = "if c then { p := q } else { p := r }"
    st = Parser(src, "<t>").parse_stmt()
    scope = E.Scope()
    scope.declare("p", {"__struct__": "P", "a": 1}, None, mutable=True)
    scope.declare("q", {"__struct__": "P", "a": 2}, None, mutable=False)
    scope.declare("r", {"__struct__": "P", "a": 3}, None, mutable=False)
    E._staged_if(jnp.asarray(True), st, scope, E.Ctx())
    merged = scope.lookup("p")
    assert merged["__struct__"] == "P"
    assert int(np.asarray(merged["a"])) == 2


def test_staged_if_struct_type_mismatch_diagnostic():
    """One arm assigns a struct, the other a scalar: located error."""
    import jax.numpy as jnp

    from ziria_tpu.frontend import eval as E
    from ziria_tpu.frontend.parser import Parser

    src = "if c then { p := q } else { p := 5 }"
    st = Parser(src, "<t>").parse_stmt()
    scope = E.Scope()
    scope.declare("p", {"__struct__": "P", "a": 1}, None, mutable=True)
    scope.declare("q", {"__struct__": "P", "a": 2}, None, mutable=False)
    with pytest.raises(ZiriaRuntimeError, match="struct"):
        E._staged_if(jnp.asarray(True), st, scope, E.Ctx())


def test_staged_if_shape_mismatch_diagnostic():
    import jax.numpy as jnp

    from ziria_tpu.frontend import eval as E
    from ziria_tpu.frontend.parser import Parser

    src = "if c then { a := q } else { a := r }"
    st = Parser(src, "<t>").parse_stmt()
    scope = E.Scope()
    scope.declare("a", np.zeros(2), None, mutable=True)
    scope.declare("q", np.zeros(2), None, mutable=False)
    scope.declare("r", np.zeros(3), None, mutable=False)
    with pytest.raises(ZiriaRuntimeError, match="incompatible shapes"):
        E._staged_if(jnp.asarray(True), st, scope, E.Ctx())
