"""Lane-vector lowering of statement for-loops (frontend/eval.py
`_vectorized_for`) — the reference vectorizer's widening applied to
statement loops: eligible bodies run as one vector pass (gathers,
per-lane selects, scatters, induction closed forms) instead of a
lax.fori_loop of scalar ops. The contract is BIT-exactness with both
the unvectorized staging (ZIRIA_NO_VECTOR_LOOPS=1) and the
interpreter oracle — including sequential float-accumulation rounding.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from ziria_tpu.backend.execute import run_jit
from ziria_tpu.frontend import compile_source
from ziria_tpu.frontend import eval as E
from ziria_tpu.interp.interp import run


def _both(src, xs):
    prog = compile_source(src)
    want = run(prog.comp, list(xs)).out_array()
    got = np.asarray(run_jit(prog.comp, xs))
    np.testing.assert_array_equal(np.asarray(want), got)
    return got


def _engaged(src, xs, expect: bool):
    hits = []
    orig = E._vectorized_for

    def spy(start, count, st, scope, ctx):
        r = orig(start, count, st, scope, ctx)
        hits.append(r)
        return r

    E._vectorized_for = spy
    try:
        _both(src, xs)
    finally:
        E._vectorized_for = orig
    assert any(hits) == expect, hits


def test_gather_scatter_loop_vectorizes():
    # deinterleave shape: out[k] := in[f(k)] with a non-affine READ
    # index (gather) and an affine write index
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[96] int32) <- takes 96;
      var out : arr[96] int32;
      do {
        for k in [0, 96] {
          out[k] := v[(96 / 16) * (k % 16) + k / 16] * 3
        }
      };
      emits out[0, 96]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(192, dtype=np.int32) * 7) % 89, True)


def test_multi_site_strided_scatter():
    # demap shape: several affine sites with one stride, distinct
    # offsets, plus a data-dependent per-lane select
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[48] int32) <- takes 48;
      var llr : arr[144] int32;
      do {
        for d in [0, 48] {
          var t : int32 := v[d];
          if (t % 2 == 0) then { t := t * 3 } else { t := 0 - t };
          llr[3 * d] := t;
          llr[3 * d + 1] := t + 1;
          llr[3 * d + 2] := v[47 - d]
        }
      };
      emits llr[0, 144]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(96, dtype=np.int32) * 31) % 257, True)


def test_float_induction_rounds_sequentially():
    # ph := ph + eps accumulated in double: the vector pass must
    # reproduce SEQUENTIAL rounding exactly (closed form differs in
    # ulps and would diverge from the oracle)
    src = """
    let comp main = read[int32] >>> repeat {
      x <- take;
      var acc : arr[64] double;
      var ph : double := 0.1;
      do {
        for k in [0, 64] {
          acc[k] := ph * x;
          ph := ph + 0.3333333333
        }
      };
      emit int32(acc[63] * 1000.0);
      emit int32(ph * 1000.0)
    } >>> write[int32]
    """
    _engaged(src, np.arange(1, 5, dtype=np.int32), True)


def test_conditional_scatter_one_armed():
    # rotate-loop shape: one-armed if guarding an affine write
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[80] int32) <- takes 80;
      var sym : arr[64] int32;
      do {
        for k in [0, 80] {
          if (k >= 16) then { sym[k - 16] := v[k] * 2 + k }
        }
      };
      emits sym[0, 64]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(160, dtype=np.int32) * 13) % 101, True)


def test_reduction_vectorizes():
    # s := s + f(k): var-dependent int reduction — r4 general-induction
    # path (two-pass cumsum); was an r3 exclusion (VERDICT r3 next #4)
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var s : int32 := 0;
      do { for k in [0, 32] { s := s + v[k] * k } };
      emit s
    } >>> write[int32]
    """
    _engaged(src, (np.arange(64, dtype=np.int32) * 3) % 47, True)


def test_read_write_same_array_stays_fori():
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var a : arr[32] int32;
      do {
        for k in [0, 32] { a[k] := v[k] };
        for k in [0, 32] {
          a[(k * 7) % 32] := a[(k * 5) % 32] + 1
        }
      };
      emits a[0, 32]
    } >>> write[int32]
    """
    # second loop reads AND writes `a`; also indices are non-affine —
    # correctness over speed
    _both(src, (np.arange(64, dtype=np.int32) * 3) % 47)


def test_colliding_sites_stay_fori():
    # two sites with the same stride and SAME offset mod stride could
    # collide across lanes — must stay sequential
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var a : arr[80] int32;
      do {
        for k in [0, 32] {
          a[2 * k] := v[k];
          a[2 * k + 2] := v[k] * 5
        }
      };
      emits a[0, 80]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(64, dtype=np.int32) * 3) % 47, False)


def test_kill_switch_env_var():
    code = textwrap.dedent("""
        import jax; jax.config.update("jax_platforms", "cpu")
        import numpy as np
        from ziria_tpu.backend.execute import run_jit
        from ziria_tpu.frontend import compile_source
        from ziria_tpu.frontend import eval as E
        src = '''
        let comp main = read[int32] >>> repeat {
          (v : arr[96] int32) <- takes 96;
          var out : arr[96] int32;
          do { for k in [0, 96] { out[k] := v[95 - k] } };
          emits out[0, 96]
        } >>> write[int32]
        '''
        hits = []
        orig = E._vectorized_for
        def spy(*a):
            r = orig(*a)
            hits.append(r)
            return r
        E._vectorized_for = spy
        xs = np.arange(96, dtype=np.int32)
        run_jit(compile_source(src).comp, xs)
        assert not any(hits), hits
        print("disabled ok")
    """)
    env = dict(os.environ, ZIRIA_NO_VECTOR_LOOPS="1",
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-800:]
    assert "disabled ok" in r.stdout


@pytest.mark.parametrize("seed", range(12))
def test_fuzz_vector_loops_match_unvectorized(seed):
    # random eligible-ish bodies: the vector pass (when it engages)
    # must equal the interpreter exactly; ineligible shapes must fall
    # back silently
    rng = np.random.default_rng(5000 + seed)
    n = int(rng.choice([24, 48, 96]))
    stride = int(rng.choice([1, 2, 3]))
    off = int(rng.integers(0, stride)) if stride > 1 else 0
    mul = int(rng.integers(1, 7))
    th = int(rng.integers(0, n))
    src = f"""
    let comp main = read[int32] >>> repeat {{
      (v : arr[{n}] int32) <- takes {n};
      var out : arr[{stride * n}] int32;
      var ph : int32 := {int(rng.integers(-5, 5))};
      do {{
        for k in [0, {n}] {{
          var t : int32 := v[k] * {mul} + ph;
          if (k >= {th}) then {{ t := t - v[{n - 1} - k] }}
          else {{ t := t + 7 }};
          out[{stride} * k + {off}] := t;
          ph := ph + {int(rng.integers(1, 4))}
        }}
      }};
      emits out[0, {stride * n}];
      emit ph
    }} >>> write[int32]
    """
    xs = rng.integers(-1000, 1000, size=2 * n).astype(np.int32)
    _both(src, xs)


def test_arm_local_shadow_does_not_leak():
    # code review r3 #1: a local declared inside an if-arm must not
    # make a later top-level write to a SAME-NAMED outer scalar look
    # local — that write is a non-induction outer write (ineligible)
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var t : int32 := 5;
      var out : arr[32] int32;
      do {
        for k in [0, 32] {
          if (v[k] > 0) then { var t : int32 := v[k] * 2; out[k] := t }
          else { out[k] := 0 - v[k] };
          t := k * 2
        }
      };
      emits out[0, 32];
      emit t
    } >>> write[int32]
    """
    xs = ((np.arange(64, dtype=np.int32) * 37) % 101) - 50
    _engaged(src, xs, False)      # outer t write is not an induction


def test_induction_step_reading_local_shadow():
    # code review r3 #2: an AFFINE induction step referencing a
    # body-local that shadows an outer name would evaluate against the
    # stale outer value — such steps now classify as GENERAL
    # inductions, whose steps evaluate per-lane in the body scope where
    # the local correctly shadows (r4); the engagement is positive and
    # the oracle comparison proves the shadow resolves right
    src = """
    let comp main = read[int32] >>> repeat {
      var w : int32 := 100;
      (v : arr[32] int32) <- takes 32;
      var s : int32 := 0;
      var out : arr[32] int32;
      do {
        for k in [0, 32] {
          var w : int32 := 2;
          out[k] := v[k] + s;
          s := s + w
        }
      };
      emits out[0, 32];
      emit s
    } >>> write[int32]
    """
    xs = (np.arange(32, dtype=np.int32) * 3) % 47
    _engaged(src, xs, True)


def test_static_if_fold_respects_local_shadow():
    # code review r3 #3: a statically-evaluable OUTER name shadowed by
    # a body local must not let the analysis validate the wrong arm
    src = """
    let comp main = read[int32] >>> repeat {
      let q = 0;
      (v : arr[32] int32) <- takes 32;
      var acc : int32 := 1;
      var out : arr[32] int32;
      do {
        for k in [0, 32] {
          var q : int32 := v[k] % 2;
          if (q == 0) then { out[k] := v[k] }
          else { acc := acc * 2; out[k] := 0 }
        }
      };
      emits out[0, 32];
      emit acc
    } >>> write[int32]
    """
    xs = (np.arange(32, dtype=np.int32) * 3) % 47
    # conditional outer-scalar write in the live (dynamic) arm: must
    # NOT vectorize, and results must match the oracle exactly
    _engaged(src, xs, False)


def test_depuncture_shape_vectorizes():
    # THE target shape (VERDICT r3 next #4): conditional int induction
    # `src := src + 1` under a per-lane guard, with same-site writes in
    # opposite arms (collapsed by structural index equality) and a
    # gather at the induction's per-lane value
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[72] int32) <- takes 72;
      var dep : arr[96] int32;
      var src : int32 := 0;
      do {
        for t in [0, 96] {
          var keep : int32 := 1;
          if (t % 4 == 3) then { keep := 0 };
          if (keep == 1) then {
            dep[t] := v[src];
            src := src + 1
          } else { dep[t] := 0 - 999 }
        }
      };
      emits dep[0, 96];
      emit src
    } >>> write[int32]
    """
    _engaged(src, (np.arange(144, dtype=np.int32) * 13) % 201, True)


def test_conditional_reduction_vectorizes():
    # data-dependent guard on the reduction site: the mask comes from
    # the stream, lanes contribute selectively
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[64] int32) <- takes 64;
      var s : int32 := 0;
      var t : int32 := 100;
      do {
        for k in [0, 64] {
          if (v[k] % 3 == 0) then { s := s + v[k] }
          else { t := t - 1 }
        }
      };
      emit s;
      emit t
    } >>> write[int32]
    """
    _engaged(src, (np.arange(128, dtype=np.int32) * 7) % 53, True)


def test_float_general_induction_stays_fori():
    # float reduction with var-dependent step: lane cumsum would round
    # differently than the sequential loop — must NOT engage
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var s : double := 0.0;
      var out : arr[32] int32;
      do {
        for k in [0, 32] {
          s := s + double(v[k]) * 0.1;
          out[k] := v[k] + int(s)
        }
      };
      emits out[0, 32]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(32, dtype=np.int32) * 3) % 17, False)


def test_guard_reading_induction_stays_fori():
    # discovery stability: the if condition reads the general induction
    # var itself (via nothing else), so pass-1 masks would be computed
    # from wrong-prefix values — must NOT engage
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var s : int32 := 0;
      var out : arr[32] int32;
      do {
        for k in [0, 32] {
          if (s % 2 == 0) then { s := s + v[k] };
          out[k] := s
        }
      };
      emits out[0, 32]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(32, dtype=np.int32) * 5) % 29, False)


def test_guard_reading_induction_via_local_stays_fori():
    # taint flows through a body-local: h := s; if (h > 3) ...
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var s : int32 := 0;
      var out : arr[32] int32;
      do {
        for k in [0, 32] {
          let h = s + v[k];
          if (h > 3) then { out[k] := 1 } else { out[k] := 0 };
          s := s + v[k]
        }
      };
      emits out[0, 32]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(32, dtype=np.int32) * 5) % 7, False)


def test_rmw_same_site_vectorizes():
    # read-modify-write at the SAME affine site: each lane reads only
    # what it wrote / the original — in-place accumulate pattern
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[64] int32) <- takes 64;
      var a : arr[64] int32;
      do {
        for k in [0, 64] { a[k] := k };
        for k in [0, 64] { a[k] := a[k] + v[k] * 3 }
      };
      emits a[0, 64]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(64, dtype=np.int32) * 11) % 103, True)


def test_rmw_offset_read_nonmultiple_stride_vectorizes():
    # stride-2 writes, read at the other parity: (br-bw) % 2 != 0
    # proves no cross-lane collision
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var a : arr[66] int32;
      do {
        for k in [0, 33] { a[2 * k] := 7 };
        for k in [0, 32] { a[2 * k] := a[2 * k + 1] + v[k] }
      };
      emits a[0, 66]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(32, dtype=np.int32) * 3) % 19, True)


def test_rmw_cross_lane_read_stays_fori():
    # reads a DIFFERENT lane's write site (offset differs by a
    # multiple of the stride): sequential sees iteration order, the
    # vector pass cannot — must NOT engage
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[32] int32) <- takes 32;
      var a : arr[34] int32;
      do {
        for k in [0, 32] { a[k + 2] := a[k] + v[k] }
      };
      emits a[0, 34]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(32, dtype=np.int32) * 9) % 41, False)


def test_folded_guard_on_body_written_var_stays_folded_safe():
    # r4 hardening: a statically-evaluable condition that reads a
    # variable the BODY writes must not freeze a branch (the pre-loop
    # value would pick one arm for every lane while sequential
    # execution flips arms mid-loop)
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[16] int32) <- takes 16;
      var s : int32 := 0;
      var out : arr[16] int32;
      do {
        for k in [0, 16] {
          if (s > 3) then { out[k] := v[k] } else { out[k] := 0 - v[k] };
          s := s + 1
        }
      };
      emits out[0, 16]
    } >>> write[int32]
    """
    # engagement either way is fine — exactness vs the oracle is the
    # contract (the guard now reads a body-written var, so the fold is
    # suppressed and the if runs per-lane)
    _both(src, (np.arange(16, dtype=np.int32) * 3) % 23 + 1)


def test_general_induction_ab_exact_fuzz():
    # A/B: vectorized vs ZIRIA_NO_VECTOR_LOOPS staging, random bodies
    # with conditional inductions — run in-process both ways
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[64] int32) <- takes 64;
      var dep : arr[96] int32;
      var sel : int32 := 0;
      var tot : int32 := 0;
      do {
        for t in [0, 96] {
          var keep : int32 := 1;
          if (t % 6 == 3 || t % 6 == 4) then { keep := 0 };
          if (keep == 1) then {
            dep[t] := v[sel];
            sel := sel + 1;
            tot := tot + v[sel % 64]
          } else { dep[t] := 0 }
        }
      };
      emits dep[0, 96];
      emit sel;
      emit tot
    } >>> write[int32]
    """
    rng = np.random.default_rng(11)
    for _ in range(3):
        xs = rng.integers(-100, 100, 128).astype(np.int32)
        _both(src, xs)


def test_rmw_lane_varying_offset_stays_fori():
    # code review r4: structurally-equal read/write index `k - s` with
    # s an induction — every lane resolves to the same element, so the
    # injectivity proof fails and the loop must NOT engage
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[8] int32) <- takes 8;
      var a : arr[64] int32;
      var s : int32 := 0;
      do {
        for k in [0, 64] {
          a[k - s] := a[k - s] + v[0] + 1;
          s := s + 1
        }
      };
      emits a[0, 64]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(16, dtype=np.int32) * 3) % 11, False)


def test_scatter_lane_varying_offset_stays_fori():
    # same hole, write-only form: scatter collisions across lanes have
    # no defined order under jnp — must NOT engage
    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[64] int32) <- takes 64;
      var a : arr[64] int32;
      var s : int32 := 0;
      do {
        for k in [0, 64] {
          a[k - s] := v[k];
          s := s + 1
        }
      };
      emits a[0, 64]
    } >>> write[int32]
    """
    _engaged(src, (np.arange(64, dtype=np.int32) * 7) % 97, False)


def test_vectorized_graph_has_no_while_ops(monkeypatch):
    """The device-code claim, measured: the depuncture shape lowers to
    ZERO stablehlo.while ops when lane-vectorized (pure gather/select/
    scatter/cumsum) vs a 96-trip scalar while loop sequentially —
    per-symbol loop cost leaves the graph entirely (VERDICT r3 weak #3
    asked for this to be evidenced, not argued)."""
    import jax
    import jax.numpy as jnp
    from ziria_tpu.backend.lower import lower

    src = """
    let comp main = read[int32] >>> repeat {
      (v : arr[72] int32) <- takes 72;
      var dep : arr[96] int32;
      var src : int32 := 0;
      do {
        for t in [0, 96] {
          var keep : int32 := 1;
          if (t % 4 == 3) then { keep := 0 };
          if (keep == 1) then {
            dep[t] := v[src];
            src := src + 1
          } else { dep[t] := 0 - 999 }
        }
      };
      emits dep[0, 96]
    } >>> write[int32]
    """

    def count_whiles(no_vec):
        if no_vec:
            monkeypatch.setenv("ZIRIA_NO_VECTOR_LOOPS", "1")
        else:
            monkeypatch.delenv("ZIRIA_NO_VECTOR_LOOPS", raising=False)
        lo = lower(compile_source(src).comp, width=1)
        chunk = jnp.zeros((lo.take,), jnp.int32)
        txt = jax.jit(lo.step).lower(lo.init_carry, chunk).as_text()
        return txt.count("stablehlo.while")

    assert count_whiles(no_vec=True) >= 1
    assert count_whiles(no_vec=False) == 0


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_general_induction_rmw_shapes(seed):
    # richer fuzz shape (r4): conditional general induction `sel`,
    # gather at the induction's per-lane value, a reduction `tot`,
    # an affine induction `ph`, strided conditional scatters — all in
    # one body; soaked over 60 seeds before committing these 6
    rng = np.random.default_rng(9000 + seed)
    n = int(rng.choice([24, 48, 96]))
    stride = int(rng.choice([1, 2, 3]))
    off = int(rng.integers(0, stride)) if stride > 1 else 0
    mul = int(rng.integers(1, 7))
    th = int(rng.integers(0, n))
    period = int(rng.choice([3, 4, 6]))
    drop = int(rng.integers(0, period))
    src = f"""
    let comp main = read[int32] >>> repeat {{
      (v : arr[{n}] int32) <- takes {n};
      var out : arr[{stride * n}] int32;
      var sel : int32 := 0;
      var tot : int32 := 0;
      var ph : int32 := {int(rng.integers(-5, 5))};
      do {{
        for k in [0, {n}] {{
          var keep : int32 := 1;
          if (k % {period} == {drop}) then {{ keep := 0 }};
          var t : int32 := v[k] * {mul} + ph;
          if (k >= {th}) then {{ t := t - v[{n - 1} - k] }}
          else {{ t := t + 7 }};
          if (keep == 1) then {{
            out[{stride} * k + {off}] := t + v[sel % {n}];
            sel := sel + 1
          }} else {{ out[{stride} * k + {off}] := 0 - 1 }};
          tot := tot + v[k] % 13;
          ph := ph + 1
        }}
      }};
      emits out[0, {stride * n}];
      emit sel + tot
    }} >>> write[int32]
    """
    xs = rng.integers(-1000, 1000, size=2 * n).astype(np.int32)
    _both(src, xs)
