"""Multi-stream fleet receiver (backend/framebatch.receive_streams +
MultiStreamReceiver + rx._jit_stream_chunk_multi/_jit_stream_decode_multi):
S concurrent I/Q streams' chunks stacked on a leading stream axis
through stream-axis-vmapped twins of the two compiled streaming
programs — <= 2 device dispatches per CHUNK-STEP independent of S —
with every emitted frame bit-identical, lane for lane and RxResult
field for field, to S independent single-stream `StreamReceiver`s
(and hence, transitively, to per-capture `rx.receive` over the
slice — the PR 5 contract).

Budget discipline (the tier-1 870 s cutoff is real): ONE module
fixture pays the S=8 fleet compiles at the suite-shared streaming
geometry (chunk 4096, window 1024, K=8, 8-symbol bucket — the same
keys test_rx_stream and test_programs share), covering mixed rates
(all 8 across the fleet), a chunk-boundary-straddling frame, an
all-noise stream, an EMPTY stream, and ragged lengths. The sharded
run (frame_mesh(8), one stream per virtual device) and the S=1 pin
compile their own (small) programs; everything else re-dispatches.
"""

import numpy as np
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import link
from ziria_tpu.phy.wifi import rx
from ziria_tpu.utils import dispatch

N_BYTES = 12     # +4 FCS = the suite's standard 16-byte on-air PSDU
CHUNK, FRAME_LEN, K, S = 4096, 1024, 8, 8
GEO = dict(chunk_len=CHUNK, frame_len=FRAME_LEN,
           max_frames_per_chunk=K, check_fcs=True)


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


def _same_frames(got, want) -> None:
    assert [f.start for f in got] == [f.start for f in want]
    for a, b in zip(got, want):
        assert _same_result(a.result, b.result)


@pytest.fixture(scope="module")
def corpus():
    """An 8-stream fleet load: all 8 rates spread across the streams,
    one stream whose second frame straddles its chunk boundary, one
    all-noise stream, one EMPTY stream, ragged lengths — plus one
    fleet pass and one S-independent-receivers oracle pass, both
    under dispatch counters."""
    rng = np.random.default_rng(20260803)

    def psdus(n):
        return [rng.integers(0, 256, N_BYTES).astype(np.uint8)
                for _ in range(n)]

    per_psdus = [psdus(2), psdus(2), [], psdus(3), psdus(2),
                 psdus(1), [], psdus(2)]
    per_rates = [[6, 54], [54, 54], [], [24, 36, 48], [9, 12],
                 [18], [], [48, 6]]
    per_gaps = [None, [3260], None, None, None, None, None, None]
    per_delay = [60, 60, 0, 500, 1500, 30, 0, 100]
    streams, starts = [], []
    for i in range(S):
        if not per_psdus[i]:
            streams.append(np.zeros((0, 2), np.float32))
            starts.append(np.zeros((0,), np.int64))
            continue
        st, sts = link.stream_many(
            per_psdus[i], per_rates[i], gaps=per_gaps[i],
            snr_db=30.0, cfo=1e-4, delay=per_delay[i],
            seed=40 + i, add_fcs=True, tail=FRAME_LEN)
        streams.append(st)
        starts.append(sts)
    # stream 2: noise, no frames (long enough to own a full chunk)
    streams[2] = rng.normal(scale=0.05, size=(CHUNK + 2000, 2)) \
        .astype(np.float32)
    # the straddle stream really straddles: frame 1 starts inside
    # chunk 0's overlap and crosses the 4096 boundary (the
    # test_rx_stream recipe, here as ONE lane of the fleet)
    assert starts[1][1] == 3800 and starts[1][1] + 480 > CHUNK

    with dispatch.count_dispatches() as d_m:
        res_m, st_m = framebatch.receive_streams(streams, multi=True,
                                                 **GEO)
    with dispatch.count_dispatches() as d_o:
        res_o, st_o = framebatch.receive_streams(streams, multi=False,
                                                 **GEO)
    return streams, starts, res_m, st_m, d_m, res_o, st_o, d_o


def test_fleet_bit_identical_to_s_independent_receivers(corpus):
    # THE fleet contract: per stream, frame for frame, every emitted
    # start and RxResult (crc_ok included) equals what a lone
    # single-stream receiver emits — mixed rates, straddle, noise,
    # empty, and ragged lengths all riding one stream axis
    streams, starts, res_m, _st, _d, res_o, _so, _do = corpus
    assert len(res_m) == len(res_o) == S
    for i in range(S):
        _same_frames(res_m[i], res_o[i])
        assert [f.start for f in res_m[i]] == list(starts[i])
    # all 8 rates decoded somewhere in the fleet
    got_rates = sorted(f.result.rate_mbps
                       for r in res_m for f in r if f.result.ok)
    assert set(got_rates) == {6, 9, 12, 18, 24, 36, 48, 54}
    # noise and empty streams emit nothing, in both paths
    assert res_m[2] == [] and res_m[6] == []


def test_straddling_frame_decoded_exactly_once_in_fleet(corpus):
    streams, starts, res_m, _st, _d, _ro, _so, _do = corpus
    assert [f.start for f in res_m[1]] == list(starts[1])
    for f in res_m[1]:
        assert f.result.ok and f.result.crc_ok
        ref = rx.receive(streams[1][f.start: f.start + FRAME_LEN],
                         check_fcs=True)
        assert _same_result(f.result, ref)


def test_dispatches_per_chunk_step_independent_of_s(corpus):
    # the tentpole number at S=8: <= 2 dispatches per CHUNK-STEP
    # (one stacked scan + at most one flattened decode), however many
    # streams ride the step — vs the oracle's per-stream chunk costs
    _s, _starts, _rm, st_m, d_m, _ro, st_o, d_o = corpus
    assert st_m.streams == S and st_m.chunk_steps >= 2
    assert d_m.total <= 2 * st_m.chunk_steps, dict(d_m.counts)
    assert d_m.counts["rx.stream_chunk_multi"] == st_m.chunk_steps
    assert d_m.counts["rx.stream_decode_multi"] <= st_m.chunk_steps
    # the oracle pays one scan per PER-STREAM chunk: strictly more
    # scans than the fleet's chunk-steps (7 non-empty streams)
    assert d_o.counts["rx.stream_chunk"] == st_o.chunk_steps
    assert st_o.chunk_steps > st_m.chunk_steps
    assert st_m.frames == st_o.frames
    # double-buffering still overlaps at fleet scale
    assert d_m.gauges["rx.stream_inflight"] == 2
    assert st_m.max_in_flight == 2
    assert st_m.overflow_chunks == 0


def test_active_streams_gauge_and_per_stream_carry_rows(corpus):
    # the telemetry satellite: the fleet records an rx.active_streams
    # level per chunk-step (aggregate row) plus per-stream carry-depth
    # labels (the per-stream rows trace_report renders alongside)
    _s, _starts, _rm, st_m, d_m, _ro, _so, _do = corpus
    assert d_m.gauges["rx.active_streams"] == st_m.max_active_streams
    assert 2 <= st_m.max_active_streams <= S
    assert "rx.stream_carry_depth" in d_m.gauges
    per = [k for k in d_m.gauges
           if k.startswith("rx.stream_carry_depth[s")]
    assert per, sorted(d_m.gauges)
    # the empty stream never rides a step, so it has no carry row
    assert "rx.stream_carry_depth[s6]" not in d_m.gauges


def test_dispatch_pin_at_s1(corpus):
    # S=1 is the degenerate fleet: same <= 2-per-chunk-step pin, and
    # bit-identity with the single-stream receiver it wraps
    streams, _starts, _rm, _st, _d, res_o, _so, _do = corpus
    with dispatch.count_dispatches() as d1:
        res_1, st_1 = framebatch.receive_streams(streams[:1],
                                                 multi=True, **GEO)
    assert st_1.streams == 1 and st_1.chunk_steps >= 1
    assert d1.total <= 2 * st_1.chunk_steps, dict(d1.counts)
    _same_frames(res_1[0], res_o[0])


def test_sharded_fleet_on_suite_mesh_bit_identical(corpus):
    # the dp-mesh path: the SAME fleet with its stream axis sharded
    # over the suite's 8 virtual devices (one stream per device,
    # shard_map via the compat shim) — identical per-device program,
    # streams independent, so results are bit-identical lane for lane
    # and the dispatch pin is unchanged
    from ziria_tpu.parallel.batch import frame_mesh

    streams, starts, res_m, _st, _d, _ro, _so, _do = corpus
    mesh = frame_mesh(8)
    with dispatch.count_dispatches() as d_sh:
        res_s, st_s = framebatch.receive_streams(
            streams, multi=True, mesh=mesh, **GEO)
    assert d_sh.total <= 2 * st_s.chunk_steps, dict(d_sh.counts)
    for i in range(S):
        _same_frames(res_s[i], res_m[i])
        assert [f.start for f in res_s[i]] == list(starts[i])


def test_all_noise_fleet_costs_one_dispatch_per_step(corpus):
    # the noise fast path survives the fleet: a chunk-step with zero
    # decodable lanes across ALL streams skips the decode dispatch
    # entirely (geometry shared with the fixture: zero new compiles)
    rng = np.random.default_rng(31)
    noise = [rng.normal(scale=0.05, size=(2 * CHUNK, 2))
             .astype(np.float32) for _ in range(S)]
    with dispatch.count_dispatches() as d:
        res, stats = framebatch.receive_streams(noise, multi=True,
                                                **GEO)
    assert all(r == [] for r in res)
    assert stats.frames == 0 and stats.overflow_chunks == 0
    assert d.total == stats.chunk_steps
    assert d.counts.get("rx.stream_decode_multi", 0) == 0


def test_ragged_pushes_thread_carries_no_recompile(corpus):
    """The push-driven fleet surface: the same 8 streams fed in
    ragged per-stream slabs through ONE MultiStreamReceiver emit the
    same frames as the one-shot call, per-stream (tail, offset,
    emitted, watermark) carries threading across chunk-steps. The
    whole steady state runs under dispatch.no_recompile: at the
    fixture's already-compiled geometry, ragged arrival may only
    RE-DISPATCH the two compiled fleet programs."""
    streams, _starts, res_m, _st, _d, _ro, _so, _do = corpus
    with dispatch.no_recompile(rx._jit_stream_chunk_multi,
                               rx._jit_stream_decode_multi):
        msr = framebatch.MultiStreamReceiver(S, **GEO)
        got = []
        for a, b in [(0, 500), (500, 3500), (3500, 4200),
                     (4200, 7000), (7000, None)]:
            for i in range(S):
                got += msr.push(i, streams[i][a:b])
        got += msr.flush()
    per = [[] for _ in range(S)]
    for i, fr in got:
        per[i].append(fr)
    for i in range(S):
        _same_frames(per[i], res_m[i])
        c = msr.carry(i)
        assert c.offset + c.tail.shape[0] == streams[i].shape[0]
        assert c.emitted == len(res_m[i])
    # the dedupe watermark is per stream: streams that drained a
    # chunk-step carry the prune bound forward
    assert msr.carry(1).watermark > 0
    assert msr.carry(6).watermark == 0          # empty stream
    with pytest.raises(RuntimeError):
        msr.push(0, streams[0][:8])             # closed fleet
    with pytest.raises(RuntimeError):
        msr.push_many([s[:0] for s in streams])


def test_single_stream_carry_exposes_watermark(corpus):
    # the StreamCarry watermark satellite reaches the single-stream
    # receiver too (same fixture geometry: re-dispatch only)
    streams, _starts, _rm, _st, _d, _ro, _so, _do = corpus
    sr = framebatch.StreamReceiver(**GEO)
    sr.push(streams[1])
    sr.flush()
    assert sr.carry.watermark > 0
    assert sr.carry.emitted == 2


def test_multi_stream_env_knob(monkeypatch):
    # the CLI's scoped-env pattern: default ON, ZIRIA_MULTI_STREAM=0
    # forces the S-independent-receivers oracle, an explicit argument
    # wins; any nonzero lane count means ON
    monkeypatch.delenv("ZIRIA_MULTI_STREAM", raising=False)
    assert framebatch.multi_stream_enabled(None)
    monkeypatch.setenv("ZIRIA_MULTI_STREAM", "0")
    assert not framebatch.multi_stream_enabled(None)
    assert framebatch.multi_stream_enabled(True)
    monkeypatch.setenv("ZIRIA_MULTI_STREAM", "8")
    assert framebatch.multi_stream_enabled(None)
    assert not framebatch.multi_stream_enabled(False)


def test_cli_multi_stream_flag_scopes_env(tmp_path, monkeypatch):
    """--multi-stream S writes ZIRIA_MULTI_STREAM for the invocation
    only (the scoped-env pattern): a pre-existing value is restored
    after main() returns, and --no-multi-stream maps to the "0"
    force-off value."""
    import os

    from ziria_tpu.runtime.buffers import StreamSpec, write_stream
    from ziria_tpu.runtime.cli import build_parser, main as cli_main

    args = build_parser().parse_args(["--multi-stream", "4"])
    assert args.multi_stream == 4
    args = build_parser().parse_args(["--no-multi-stream"])
    assert args.multi_stream == 0

    inf, outf = tmp_path / "in.dbg", tmp_path / "out.dbg"
    rng = np.random.default_rng(0)
    write_stream(StreamSpec(ty="bit", path=str(inf), mode="dbg"),
                 rng.integers(0, 2, 16).astype(np.uint8))
    monkeypatch.setenv("ZIRIA_MULTI_STREAM", "0")
    rc = cli_main([
        "--prog=scramble",
        "--input=file", f"--input-file-name={inf}",
        "--input-file-mode=dbg", "--input-type=bit",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=dbg", "--output-type=bit",
        "--backend=interp", "--multi-stream", "4",
    ])
    assert rc == 0
    assert os.environ.get("ZIRIA_MULTI_STREAM") == "0"   # restored


def test_bad_geometry_and_mesh_divisibility_rejected():
    with pytest.raises(ValueError):
        framebatch.MultiStreamReceiver(0, **GEO)
    with pytest.raises(ValueError):
        framebatch.MultiStreamReceiver(2, chunk_len=4096,
                                       frame_len=1000)
    with pytest.raises(ValueError):
        framebatch.MultiStreamReceiver(2, chunk_len=1024,
                                       frame_len=1024)
    from ziria_tpu.parallel.batch import frame_mesh
    with pytest.raises(ValueError):
        framebatch.MultiStreamReceiver(5, mesh=frame_mesh(8), **GEO)
    # a mesh cannot ride the S-independent-receivers oracle: loud, not
    # a silently unsharded measurement
    with pytest.raises(ValueError):
        framebatch.receive_streams(
            [np.zeros((8, 2), np.float32)], multi=False,
            mesh=frame_mesh(8), **GEO)
    msr = framebatch.MultiStreamReceiver(2, **GEO)
    with pytest.raises(IndexError):
        msr.push(2, np.zeros((4, 2), np.float32))
    with pytest.raises(ValueError):
        msr.push_many([np.zeros((4, 2), np.float32)])
    per, stats = framebatch.receive_streams([], **GEO)
    assert per == [] and stats.streams == 0


def test_stream_many_multi_synthesizer_contract():
    # per-stream folded seeds: independent reproducible lanes, no
    # aliasing of the base seed; broadcast per-stream channel params;
    # shape errors loud
    rng = np.random.default_rng(7)
    pp = [[rng.integers(0, 256, N_BYTES).astype(np.uint8)],
          [rng.integers(0, 256, N_BYTES).astype(np.uint8)]]
    streams, starts = link.stream_many_multi(
        pp, [[6], [54]], snr_db=30.0, cfo=[1e-4, -1e-4],
        delay=[60, 90], seed=3, add_fcs=True, tail=FRAME_LEN)
    assert len(streams) == len(starts) == 2
    assert starts[0][0] == 60 and starts[1][0] == 90
    # deterministic: the same call reproduces bit-identical streams
    streams2, _ = link.stream_many_multi(
        pp, [[6], [54]], snr_db=30.0, cfo=[1e-4, -1e-4],
        delay=[60, 90], seed=3, add_fcs=True, tail=FRAME_LEN)
    assert all(np.array_equal(a, b)
               for a, b in zip(streams, streams2))
    # stream i's draws differ from the base-seed single-stream call
    solo, _ = link.stream_many(pp[0], [6], snr_db=30.0, cfo=1e-4,
                               delay=60, seed=3, add_fcs=True,
                               tail=FRAME_LEN)
    assert not np.array_equal(streams[0], solo)
    with pytest.raises(ValueError):
        link.stream_many_multi(pp, [[6]])
    with pytest.raises(ValueError):
        link.stream_many_multi(pp, [[6], [54]], gaps=[[1]])
