"""Fault-tolerant streaming runtime (utils/faults + runtime/resilience
+ the guarded framebatch/link surfaces; docs/robustness.md):

- the chaos layer: deterministic replay by (site, seed, call-index),
  scoped activation, spec validation, the ``--chaos`` grammar, and the
  pinned free-when-idle seam overhead (the PR 7 discipline extended to
  the fault seams);
- guarded dispatch: transient retry with deterministic-jitter backoff,
  fatal/exhausted classification, the watchdog cutting a hung
  dispatch, and fallback wiring;
- push-seam input validation: malformed/non-finite slabs rejected
  with the stream NAMED, ``sanitize=True`` zero-and-quarantine, fleet
  ``push_many`` dict form with a named unknown-id error;
- lane quarantine: a poisoned fleet stream rides behind the
  valid-mask, healthy lanes stay LANE-FOR-LANE BIT-IDENTICAL to an
  unquarantined run, and the stream rejoins after N clean chunks;
- chaos matrix over the compiled streaming programs: a transient
  fault inside the chunk scan retries to identical frames; a fatal
  decode fault degrades to the per-capture oracle (bit-identical by
  the pinned contract) with the degraded gauge recorded; a fatal scan
  fault degrades to the eager twin; an injected hang is cut by the
  watchdog and retried;
- the fused-link and sweep surfaces under injection (transient →
  identical result, fatal → staged-oracle / loop degrade, never a
  silent wrong answer);
- carry checkpoint/restore: a receiver restarted from a checkpoint
  emits bit-identical subsequent frames vs an uninterrupted run.

Budget discipline: the streaming tests ride the suite-shared
geometry (chunk 4096 / window 1024 / K=8 / 12-byte+FCS PSDUs — the
test_rx_stream keys) and the fused-link/sweep tests reuse
test_link_fused's exact LENS/MBPS/sweep geometry, so in one tier-1
process every compiled program here is a jit-cache hit.
"""

import time

import numpy as np
import pytest

from ziria_tpu.backend import framebatch
from ziria_tpu.phy import link
from ziria_tpu.runtime import resilience
from ziria_tpu.utils import dispatch, faults, telemetry

N_BYTES = 12
CHUNK, FRAME_LEN, K = 4096, 1024, 8
GEO = dict(chunk_len=CHUNK, frame_len=FRAME_LEN,
           max_frames_per_chunk=K, check_fcs=True)

# test_link_fused's exact fused-graph geometry: shared compile class
LENS = (16, 10, 16, 5, 16, 12, 9, 16)
MBPS_ALL = (6, 9, 12, 18, 24, 36, 48, 54)
CFO = tuple((-1) ** k * 1e-4 * (k + 1) for k in range(8))
DELAY = tuple(20 + 17 * k for k in range(8))
SNRS = (25.0, 30.0, -25.0, 28.0, 25.0, 30.0, 27.0, 26.0)


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


def _same_frames(got, want) -> None:
    assert [f.start for f in got] == [f.start for f in want]
    for a, b in zip(got, want):
        assert _same_result(a.result, b.result)


@pytest.fixture(scope="module")
def corpus():
    """One mixed-rate single stream + its clean streaming run, and an
    S=4 fleet + its clean run — every chaos test replays against
    these references at the suite-shared geometry."""
    rng = np.random.default_rng(20260804)
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in range(4)]
    stream, starts = link.stream_many(
        psdus, [6, 54, 24, 54], snr_db=30.0, cfo=1e-4, delay=80,
        seed=21, add_fcs=True, tail=FRAME_LEN)
    frames_c, stats_c = framebatch.receive_stream(stream, **GEO)
    assert [f.start for f in frames_c] == list(starts)
    assert all(f.result.ok and f.result.crc_ok for f in frames_c)

    s_psdus = [[rng.integers(0, 256, N_BYTES).astype(np.uint8)
                for _ in range(2)] for _ in range(4)]
    s_rates = [[6, 54], [12, 24], [36, 48], [9, 18]]
    # stream 0's second frame sits ~3 chunks downstream (gap 9000):
    # the quarantine test needs frames BOTH before poisoning and
    # after the rejoin point, several chunk-steps apart
    streams, fstarts = link.stream_many_multi(
        s_psdus, s_rates, snr_db=30.0, cfo=1e-4, delay=60, seed=33,
        add_fcs=True, tail=FRAME_LEN,
        gaps=[[9000], None, None, None])
    res_c, st_c = framebatch.receive_streams(streams, multi=True,
                                             **GEO)
    for i in range(4):
        assert [f.start for f in res_c[i]] == list(fstarts[i])
    return stream, starts, frames_c, streams, fstarts, res_c


# ------------------------------------------------------------ chaos layer


def test_fault_plan_deterministic_replay():
    specs = (faults.FaultSpec("rx.stream_chunk", "transient", every=3),
             faults.FaultSpec("rx.push.s*", "nan_slab", calls=(1,)))

    def run():
        fired, slabs = [], []
        with faults.inject(*specs, seed=7) as plan:
            for i in range(9):
                try:
                    faults.maybe_fail("rx.stream_chunk")
                except faults.InjectedTransientError:
                    fired.append(i)
            a = np.ones((16, 2), np.float32)
            for _ in range(3):
                slab, _k = faults.corrupt_slab("rx.push.s0", a)
                slabs.append(slab)
        return fired, slabs, list(plan.fired)

    f1, s1, log1 = run()
    f2, s2, log2 = run()
    assert f1 == f2 == [2, 5, 8]
    assert log1 == log2
    # the nan_slab fired on call 1 only, same rows both replays
    assert not np.isnan(s1[0]).any() and not np.isnan(s1[2]).any()
    assert np.isnan(s1[1]).any()
    assert np.array_equal(np.isnan(s1[1]), np.isnan(s2[1]))
    # inactive outside the scope
    assert not faults.active()
    faults.maybe_fail("rx.stream_chunk")      # no-op, no raise


def test_fault_spec_validation_and_truncate():
    with pytest.raises(ValueError):
        faults.FaultPlan((faults.FaultSpec("x", "explode", every=1),))
    with pytest.raises(ValueError):      # zero selectors
        faults.FaultPlan((faults.FaultSpec("x", "transient"),))
    with pytest.raises(ValueError):      # two selectors
        faults.FaultPlan((faults.FaultSpec("x", "transient", every=2,
                                           p=0.5),))
    a = np.ones((16, 2), np.float32)
    with faults.inject(faults.FaultSpec("rx.push*", "truncate",
                                        every=1, fraction=0.25)):
        t, kinds = faults.corrupt_slab("rx.push.s3", a)
    assert t.shape[0] == 12 and kinds == ("truncate",)
    # count= bounds total firings
    with faults.inject(faults.FaultSpec("s", "transient", every=1,
                                        count=1)) as plan:
        with pytest.raises(faults.InjectedTransientError):
            faults.maybe_fail("s")
        faults.maybe_fail("s")           # budget spent: no raise
    assert plan.total_fired == 1


def test_parse_chaos_spec_and_env(monkeypatch):
    specs, seed = faults.parse_chaos_spec(
        "seed=3;rx.stream_chunk:transient:every=7;"
        "rx.push.s*:nan_slab:calls=1+4,frac=0.5")
    assert seed == 3
    assert specs[0] == faults.FaultSpec("rx.stream_chunk", "transient",
                                        every=7)
    assert specs[1].calls == (1, 4) and specs[1].fraction == 0.5
    # a bare spec fires every call
    (sp,), _ = faults.parse_chaos_spec("link.fused:fatal")
    assert sp.every == 1
    with pytest.raises(ValueError):
        faults.parse_chaos_spec("justasite")
    with pytest.raises(ValueError):
        faults.parse_chaos_spec("s:transient:bogus=1")
    monkeypatch.delenv("ZIRIA_CHAOS", raising=False)
    assert faults.env_chaos() is None
    monkeypatch.setenv("ZIRIA_CHAOS", "s:transient:every=2")
    specs, seed = faults.env_chaos()
    assert specs[0].every == 2 and seed == 0


# -------------------------------------------------------- guarded dispatch


def test_guarded_retries_transient_then_recovers():
    calls = []
    slept = []

    def fn(x):
        calls.append(x)
        return x * 2

    pol = resilience.FaultPolicy(max_retries=2, backoff_base_s=1e-4)
    with telemetry.collect() as reg:
        with faults.inject(faults.FaultSpec("site", "transient",
                                            calls=(0, 1))):
            out = resilience.guarded("site", fn, 21, policy=pol,
                                     _sleep=slept.append)
    assert out == 42 and calls == [21]
    assert len(slept) == 2
    # deterministic-jitter backoff: exact replay values, exponential
    assert slept[0] == resilience.backoff_delay("site", 0, pol)
    assert slept[1] == resilience.backoff_delay("site", 1, pol)
    assert 0.5 * 1e-4 <= slept[0] <= 1e-4 < slept[1]
    # telemetry: retries counted, backoff histogram fed, recovery noted
    snap = reg.snapshot()
    assert snap["resilience.retries"] == 2
    assert snap["resilience.recovered"] == 1
    assert snap["resilience.backoff_seconds"]["count"] == 2


def test_guarded_fatal_and_exhaustion():
    def fn():
        return "fine"

    # fatal: no retries, fallback taken immediately
    with faults.inject(faults.FaultSpec("s2", "fatal", every=1)):
        out = resilience.guarded("s2", fn, fallback=lambda: "twin",
                                 _sleep=lambda s: None)
    assert out == "twin"
    # exhausted transients raise DispatchFailed with the cause chained
    with faults.inject(faults.FaultSpec("s3", "transient", every=1)):
        with pytest.raises(resilience.DispatchFailed) as ei:
            resilience.guarded(
                "s3", fn,
                policy=resilience.FaultPolicy(max_retries=1,
                                              backoff_base_s=1e-5),
                _sleep=lambda s: None)
    assert ei.value.attempts == 2 and ei.value.kind == "transient"
    assert isinstance(ei.value.last, faults.InjectedTransientError)
    # every guarded attempt is a timed dispatch at the site
    with dispatch.count_dispatches() as d:
        with faults.inject(faults.FaultSpec("s4", "transient",
                                            calls=(0,))):
            resilience.guarded("s4", fn, _sleep=lambda s: None)
    assert d.counts["s4"] == 2


def test_guarded_watchdog_cuts_hang_and_retries():
    t0 = time.perf_counter()
    with faults.inject(faults.FaultSpec("hang", "hang", calls=(0,),
                                        delay_s=5.0)):
        out = resilience.guarded(
            "hang", lambda: "ok",
            policy=resilience.FaultPolicy(max_retries=1,
                                          backoff_base_s=1e-4,
                                          timeout_s=0.1),
            _sleep=lambda s: None)
    assert out == "ok"
    assert time.perf_counter() - t0 < 3.0       # the 5s hang was cut


def test_classify_error():
    assert resilience.classify_error(ValueError("nope")) == "fatal"
    assert resilience.classify_error(
        RuntimeError("UNAVAILABLE: tunnel flap")) == "transient"
    assert resilience.classify_error(
        RuntimeError("RESOURCE_EXHAUSTED: hbm")) == "transient"
    assert resilience.classify_error(
        RuntimeError("INVALID_ARGUMENT: shape")) == "fatal"
    assert resilience.classify_error(
        resilience.DispatchTimeout("t")) == "transient"
    assert resilience.classify_error(
        faults.InjectedFatalError("INVALID_ARGUMENT: x")) == "fatal"


def test_env_max_retries(monkeypatch):
    monkeypatch.delenv("ZIRIA_MAX_RETRIES", raising=False)
    assert resilience.env_max_retries() is None
    assert resilience.default_policy().max_retries == 2
    monkeypatch.setenv("ZIRIA_MAX_RETRIES", "5")
    assert resilience.default_policy().max_retries == 5
    assert resilience.default_policy(max_retries=1).max_retries == 1
    with pytest.raises(ValueError):
        resilience.default_policy(max_retries=-1)


def test_disabled_path_overhead_pinned():
    """The PR 7 discipline extended to the fault seams: with no plan
    active, every seam is one truthiness check (< 5µs/call, generous
    CI bound ~20x measured)."""
    assert not faults.active()
    n = 20000
    arr = np.ones((4, 2), np.float32)
    t0 = time.perf_counter()
    for _ in range(n):
        faults.maybe_fail("rx.stream_chunk")
    t_fail = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n):
        faults.corrupt_slab("rx.push", arr)
    t_slab = time.perf_counter() - t0
    assert t_fail / n < 5e-6, f"maybe_fail disabled: {t_fail/n:.2e}s"
    assert t_slab / n < 5e-6, f"corrupt_slab disabled: {t_slab/n:.2e}s"


# ------------------------------------------------- push-seam validation


def test_push_rejects_malformed_and_nonfinite():
    sr = framebatch.StreamReceiver(**GEO)
    with pytest.raises(ValueError, match="stream.*shape"):
        sr.push(np.zeros((8, 3), np.float32))
    with pytest.raises(ValueError, match="not float-convertible"):
        sr.push(["not", "samples"])
    bad = np.zeros((8, 2), np.float32)
    bad[3, 0] = np.nan
    with pytest.raises(ValueError, match="non-finite"):
        sr.push(bad)
    # empty and 0-row slabs stay fine
    assert sr.push(np.zeros((0, 2), np.float32)) == []
    assert sr.push([]) == []


def test_push_many_dict_and_unknown_stream_id():
    msr = framebatch.MultiStreamReceiver(2, **GEO)
    with pytest.raises(KeyError, match="unknown stream id 7"):
        msr.push_many({7: np.zeros((4, 2), np.float32)})
    with pytest.raises(ValueError):
        msr.push_many([np.zeros((4, 2), np.float32)])   # wrong count
    bad = np.zeros((4, 2), np.float32)
    bad[0, 1] = np.inf
    with pytest.raises(ValueError, match="stream 1.*non-finite"):
        msr.push_many({1: bad})
    assert msr.push_many({0: np.zeros((4, 2), np.float32)}) == []


def test_sanitize_counts_and_quarantines():
    sr = framebatch.StreamReceiver(sanitize=True, **GEO)
    bad = np.zeros((16, 2), np.float32)
    bad[2] = np.nan
    bad[5, 0] = np.inf
    sr.push(bad)
    assert sr.stats.sanitized == 2 and sr.stats.quarantines == 1
    assert sr._health.quarantined


# ----------------------------------------------------- lane quarantine


def test_quarantine_keeps_healthy_lanes_bit_identical(corpus):
    """THE containment contract: one stream's slab NaN-poisoned
    mid-feed (sanitize=True) → that stream quarantines behind the
    valid-mask and rejoins after N clean chunks, healthy lanes stay
    lane-for-lane bit-identical to the clean fleet run, zero crashes,
    and every frame the poisoned lane does emit matches the clean run
    (dropped-while-quarantined, never garbage)."""
    _stream, _starts, _fc, streams, fstarts, res_c = corpus
    spec = faults.FaultSpec("rx.push.s0", "nan_slab", calls=(1,),
                            fraction=0.2)
    with telemetry.collect() as reg:
        with dispatch.count_dispatches() as d:
            with faults.inject(spec, seed=5) as plan:
                msr = framebatch.MultiStreamReceiver(
                    4, sanitize=True, rejoin_after=2, **GEO)
                got = []
                step = 1500
                hi = max(s.shape[0] for s in streams)
                for a in range(0, hi, step):
                    got += msr.push_many(
                        [s[a: a + step] for s in streams])
                got += msr.flush()
    assert plan.total_fired == 1
    per = [[] for _ in range(4)]
    for i, fr in got:
        per[i].append(fr)
    # healthy lanes: bit-identical to the clean fleet run
    for i in (1, 2, 3):
        _same_frames(per[i], res_c[i])
    # the poisoned lane: a strict subset of its clean frames — the
    # frame in the quarantined window dropped, each surviving frame
    # bit-identical (zero garbage emissions)
    clean_by_start = {f.start: f for f in res_c[0]}
    for f in per[0]:
        assert f.start in clean_by_start
        assert _same_result(f.result, clean_by_start[f.start].result)
    assert len(per[0]) < len(res_c[0])
    # ... and the stream REJOINED: its post-rejoin frame (3 chunks
    # past the poisoned slab) decoded normally
    assert per[0] and per[0][-1].start == res_c[0][-1].start
    st = msr.stats
    assert st.sanitized > 0 and st.quarantines == 1
    assert st.quarantined_streams == 0      # rejoined by stream end
    assert not msr.quarantined(0)
    assert not st.degraded
    # the fleet budget held: <= 2 dispatches per chunk-step under chaos
    assert d.total <= 2 * st.chunk_steps, dict(d.counts)
    # observability: quarantine gauge + sanitized counter visible
    assert d.gauges["rx.quarantined_streams"] >= 1
    snap = reg.snapshot()
    assert snap["resilience.sanitized"] == st.sanitized
    assert snap["resilience.quarantines"] == 1


def test_quarantine_rejoin_after_clean_chunks():
    h = framebatch._LaneHealth(blowup_limit=2, rejoin_after=2)
    assert not h.step(dirty=False)
    h.poison()
    assert h.quarantined and h.quarantines == 1
    assert h.step(dirty=False)       # clean 1/2, still quarantined
    assert h.step(dirty=False)       # clean 2/2: rejoin AFTER this
    assert not h.quarantined
    assert not h.step(dirty=False)
    # repeated blowups quarantine too
    h.blowup()
    assert not h.quarantined
    h.blowup()
    assert h.quarantined and h.quarantines == 2
    # a dirty chunk resets the clean streak
    assert h.step(dirty=True) and h.clean == 0
    # blowups accumulate ACROSS chunks (a chunk's blowups are
    # delivered one drain after its step — the double buffer — so a
    # per-step reset could never see two in a row)
    h2 = framebatch._LaneHealth(blowup_limit=2, rejoin_after=2)
    h2.blowup()
    assert not h2.step(dirty=False) and not h2.quarantined
    h2.blowup()
    assert h2.quarantined


# --------------------------------------------- chaos over compiled paths


def test_transient_scan_fault_retries_to_identical_frames(corpus):
    stream, starts, frames_c, *_ = corpus
    spec = faults.FaultSpec("rx.stream_chunk", "transient", every=2)
    with telemetry.collect() as reg:
        with faults.inject(spec) as plan:
            frames, stats = framebatch.receive_stream(stream, **GEO)
    assert plan.total_fired >= 1
    _same_frames(frames, frames_c)
    assert not stats.degraded
    snap = reg.snapshot()
    assert snap["resilience.retries"] == plan.total_fired
    assert snap["resilience.recovered"] == plan.total_fired


def test_fatal_decode_fault_degrades_to_oracle_identical(corpus):
    stream, starts, frames_c, *_ = corpus
    spec = faults.FaultSpec("rx.stream_decode", "fatal", every=1)
    with telemetry.collect() as reg:
        with dispatch.count_dispatches() as d:
            with faults.inject(spec) as plan:
                frames, stats = framebatch.receive_stream(stream,
                                                          **GEO)
    assert plan.total_fired >= 1
    # the oracle twin is bit-identical by the pinned contract: a
    # degraded fleet NEVER silently diverges
    _same_frames(frames, frames_c)
    assert stats.degraded
    assert d.gauges["rx.degraded_mode"] == 1.0
    snap = reg.snapshot()
    assert snap["resilience.degraded"] == 1
    assert snap["resilience.fatal"] >= 1


def test_fatal_scan_fault_degrades_to_eager_identical(corpus):
    stream, starts, frames_c, *_ = corpus
    spec = faults.FaultSpec("rx.stream_chunk", "fatal", calls=(1,))
    with dispatch.count_dispatches() as d:
        with faults.inject(spec) as plan:
            frames, stats = framebatch.receive_stream(stream, **GEO)
    assert plan.total_fired == 1
    _same_frames(frames, frames_c)
    assert stats.degraded
    # the eager twin is its own instrumented site
    assert d.counts["rx.stream_chunk.eager"] >= 1


def test_injected_hang_cut_by_watchdog_identical(corpus):
    stream, starts, frames_c, *_ = corpus
    spec = faults.FaultSpec("rx.stream_chunk", "hang", calls=(1,),
                            delay_s=5.0)
    t0 = time.perf_counter()
    with faults.inject(spec):
        sr = framebatch.StreamReceiver(watchdog_s=1.0, **GEO)
        frames = sr.push(stream)
        frames += sr.flush()
    assert time.perf_counter() - t0 < 20.0
    _same_frames(frames, frames_c)
    assert not sr.stats.degraded


class _Unpullable:
    """A device-handle stand-in whose host pull raises the way a LOST
    async dispatch does: guarded() already returned, the failure
    surfaces at np.asarray."""

    def __array__(self, *a, **k):
        raise RuntimeError("UNAVAILABLE: tunnel died mid-execution")


def test_async_pull_failure_rescans_chunk(corpus):
    """On an async backend a runtime failure surfaces at the host
    pull, AFTER the guarded dispatch returned — the receiver must
    re-dispatch the chunk (results are lost) instead of crashing,
    and the emitted frames stay bit-identical."""
    stream, starts, frames_c, *_ = corpus
    with telemetry.collect() as reg:
        sr = framebatch.StreamReceiver(**GEO)
        frames = sr.push(stream)
        # sabotage the in-flight chunk's device handles
        off, arr, valid, own_hi, _outs = sr._pending
        sr._pending = (off, arr, valid, own_hi,
                       tuple(_Unpullable() for _ in range(11)))
        frames += sr.flush()
    _same_frames(frames, frames_c)
    assert not sr.stats.degraded     # the rescan's compiled path won
    assert reg.snapshot()["resilience.async_rescans"] == 1


def test_async_pull_failure_rescans_fleet_step(corpus):
    _s, _st, _fc, streams, fstarts, res_c = corpus
    with telemetry.collect() as reg:
        msr = framebatch.MultiStreamReceiver(4, **GEO)
        got = msr.push_many([s for s in streams])
        if msr._pending is not None:
            offs, active, arrs, valid, olo, ohi, _outs = msr._pending
            msr._pending = (offs, active, arrs, valid, olo, ohi,
                            tuple(_Unpullable() for _ in range(11)))
        got += msr.flush()
    per = [[] for _ in range(4)]
    for i, fr in got:
        per[i].append(fr)
    for i in range(4):
        _same_frames(per[i], res_c[i])
    assert not msr.stats.degraded
    assert reg.snapshot()["resilience.async_rescans"] >= 1


def test_multi_transient_and_fatal_fleet_recovery(corpus):
    _s, _st, _fc, streams, fstarts, res_c = corpus
    specs = (faults.FaultSpec("rx.stream_chunk_multi", "transient",
                              calls=(0,)),
             faults.FaultSpec("rx.stream_decode_multi", "fatal",
                              calls=(0,)))
    with faults.inject(*specs) as plan:
        res, stats = framebatch.receive_streams(streams, multi=True,
                                                **GEO)
    assert plan.total_fired == 2
    for i in range(4):
        _same_frames(res[i], res_c[i])
    assert stats.degraded and stats.frames == sum(
        len(r) for r in res_c)


# ------------------------------------------- fused link + sweep chaos


@pytest.fixture(scope="module")
def fused_corpus():
    rng = np.random.default_rng(20260803)    # test_link_fused's seed
    psdus = [rng.integers(0, 256, n).astype(np.uint8) for n in LENS]
    kw = dict(snr_db=SNRS, cfo=CFO, delay=DELAY, seed=11,
              add_fcs=True, check_fcs=True)
    clean = link.loopback_many(psdus, MBPS_ALL, fused=True, **kw)
    return psdus, kw, clean


def test_fused_link_transient_retries_identical(fused_corpus):
    psdus, kw, clean = fused_corpus
    with telemetry.collect() as reg:
        with faults.inject(faults.FaultSpec("link.fused", "transient",
                                            calls=(0,))) as plan:
            got = link.loopback_many(psdus, MBPS_ALL, fused=True, **kw)
    assert plan.total_fired == 1
    for a, b in zip(got, clean):
        assert _same_result(a, b)
    assert reg.snapshot()["resilience.retries"] == 1


def test_fused_link_fatal_degrades_to_staged_identical(fused_corpus):
    psdus, kw, clean = fused_corpus
    with telemetry.collect() as reg:
        with dispatch.count_dispatches() as d:
            with faults.inject(faults.FaultSpec(
                    "link.fused", "fatal", every=1)) as plan:
                got = link.loopback_many(psdus, MBPS_ALL, fused=True,
                                         **kw)
    assert plan.total_fired == 1
    # the staged oracle result, bit-identical — with the degrade
    # RECORDED (gauge + counter), never a silent wrong answer
    for a, b in zip(got, clean):
        assert _same_result(a, b)
    assert d.gauges["link.degraded_mode"] == 1.0
    assert reg.snapshot()["link.fused_degraded"] == 1
    # the staged twin actually ran (its sites dispatched)
    assert d.counts.get("tx.encode_many", 0) >= 1


B_SWEEP, NB_SWEEP = 8, 24                  # test_link_fused geometry
SWEEP_RATES = (6, 54)


@pytest.fixture(scope="module")
def sweep_corpus():
    rng = np.random.default_rng(9)
    psdus = rng.integers(0, 256, (B_SWEEP, NB_SWEEP)).astype(np.uint8)
    snrs, seeds = (-2.0, 8.0), (7,)
    errs = link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds)
    return psdus, snrs, seeds, errs


def test_sweep_transient_retries_identical(sweep_corpus):
    psdus, snrs, seeds, errs = sweep_corpus
    with faults.inject(faults.FaultSpec("link.sweep", "transient",
                                        calls=(0,))) as plan:
        got = link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds)
    assert plan.total_fired == 1
    assert np.array_equal(got, errs)


def test_sweep_fatal_degrades_to_loop_identical(sweep_corpus):
    psdus, snrs, seeds, errs = sweep_corpus
    with dispatch.count_dispatches() as d:
        with faults.inject(faults.FaultSpec("link.sweep", "fatal",
                                            every=1)) as plan:
            got = link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds)
    assert plan.total_fired == 1
    # integer-identical error counts via the per-batch loop twin
    assert np.array_equal(got, errs)
    assert d.gauges["link.degraded_mode"] == 1.0
    assert d.counts.get("rx.decode_batch", 0) >= 1
    # the gauge is a LEVEL, not a latch: a later healthy sweep
    # re-records 0.0 (dashboards recover)
    with telemetry.collect() as reg:
        link.sweep_ber(psdus, SWEEP_RATES, snrs, seeds)
    g = reg.find(telemetry.GAUGE_METRIC, site="link.degraded_mode")
    assert g is not None and g.last == 0.0


# ------------------------------------------- checkpoint / restore


def test_checkpoint_restore_bit_identical(corpus):
    """A receiver restarted mid-stream from its checkpoint emits
    bit-identical subsequent frames vs the uninterrupted run — the
    crash-recovery contract."""
    stream, starts, frames_c, *_ = corpus
    cut = stream.shape[0] // 2
    sr1 = framebatch.StreamReceiver(**GEO)
    first = sr1.push(stream[:cut])
    state, drained = sr1.checkpoint()
    first += drained
    # "crash": sr1 is abandoned; a NEW receiver restores and resumes
    sr2 = framebatch.StreamReceiver(checkpoint=state, **GEO)
    assert sr2.carry.offset == sr1.carry.offset
    assert np.array_equal(sr2.carry.tail, sr1.carry.tail)
    rest = sr2.push(stream[cut:])
    rest += sr2.flush()
    _same_frames(first + rest, frames_c)
    assert sr2.stats.frames + len(first) == len(frames_c)


def test_checkpoint_preserves_quarantine_and_degraded_state():
    """A quarantined/degraded receiver must RESUME quarantined and
    degraded — restoring fresh health would diverge from the
    uninterrupted run (the bit-identical-resumption contract)."""
    sr = framebatch.StreamReceiver(sanitize=True, **GEO)
    bad = np.zeros((16, 2), np.float32)
    bad[3] = np.nan
    sr.push(bad)
    sr._mark_degraded(scan=False)
    state, _ = sr.checkpoint()
    sr2 = framebatch.StreamReceiver(sanitize=True, checkpoint=state,
                                    **GEO)
    assert sr2._health.quarantined and sr2._dirty
    assert sr2.stats.quarantines == 1
    assert sr2.stats.sanitized == sr.stats.sanitized == 1
    assert sr2.stats.degraded and sr2._degraded


def test_raw_carry_without_geometry_refuses_restore(corpus):
    """A blob made by hand-calling checkpoint_carry WITHOUT the
    geometry fingerprint must not restore into an arbitrary receiver
    — the mismatch gate refuses to guess."""
    stream, *_ = corpus
    sr = framebatch.StreamReceiver(**GEO)
    sr.push(stream[:CHUNK // 2])
    blob = resilience.checkpoint_carry(sr.carry, seen=sr._seen)
    with pytest.raises(resilience.CarryCheckpointError,
                       match="lacks geometry fields"):
        framebatch.StreamReceiver(checkpoint=blob, **GEO)


def test_plain_oracle_propagates_decode_blowups(corpus, monkeypatch):
    """The containment opt-in boundary: in the PLAIN streaming=False
    oracle (no sanitize, not degraded) a decode blowup propagates —
    a genuine decoder defect must surface, never masquerade as frame
    loss. With sanitize=True the same blowup is contained, counted,
    and charged to the stream's health."""
    stream, *_ = corpus
    from ziria_tpu.phy.wifi import rx as _rx

    def boom(*a, **k):
        raise RuntimeError("genuine decoder defect")

    monkeypatch.setattr(_rx, "receive", boom)
    sr = framebatch.StreamReceiver(streaming=False, **GEO)
    with pytest.raises(RuntimeError, match="genuine decoder defect"):
        sr.push(stream)
        sr.flush()
    sr2 = framebatch.StreamReceiver(streaming=False, sanitize=True,
                                    **GEO)
    frames = sr2.push(stream)
    frames += sr2.flush()
    assert frames == []                     # dropped, loudly counted
    assert sr2.stats.lane_blowups >= 2
    assert sr2.stats.quarantines >= 1       # blowup_limit=2 reached


def test_checkpoint_geometry_mismatch_rejected(corpus):
    stream, *_ = corpus
    sr = framebatch.StreamReceiver(**GEO)
    sr.push(stream[:CHUNK // 2])
    state, _ = sr.checkpoint()
    with pytest.raises(resilience.CarryCheckpointError,
                       match="geometry mismatch"):
        framebatch.StreamReceiver(
            checkpoint=state, chunk_len=2 * CHUNK,
            frame_len=FRAME_LEN, max_frames_per_chunk=K,
            check_fcs=True)
    # detector params are part of the fingerprint: a different
    # threshold detects different starts, so it must refuse too
    with pytest.raises(resilience.CarryCheckpointError,
                       match="geometry mismatch"):
        framebatch.StreamReceiver(checkpoint=state, threshold=0.95,
                                  **GEO)
    with pytest.raises(resilience.CarryCheckpointError):
        framebatch.StreamReceiver(checkpoint=b"garbage", **GEO)


def test_checkpoint_restore_quarantined_and_degraded_emissions(corpus):
    """The CROSS-PRODUCT rider restore (ISSUE 13 satellite): PR 12
    pins each rider field separately; this pins the behavior of a
    receiver that is simultaneously QUARANTINED and DEGRADED at
    checkpoint time — the restored receiver's subsequent emissions
    (quarantine drops, rejoin timing, oracle-twin decodes) are
    bit-identical to the uninterrupted quarantined+degraded run."""
    _s, _st, _fc, streams, _fs, _rc = corpus
    stream = streams[0]          # 2nd frame ~3 chunks downstream:
    #                              frames exist on BOTH sides of the
    #                              quarantine rejoin

    def run(split):
        sr = framebatch.StreamReceiver(sanitize=True, rejoin_after=2,
                                       **GEO)
        bad = np.zeros((16, 2), np.float32)
        bad[3] = np.nan
        out = sr.push(bad)                   # -> quarantined
        sr._mark_degraded(scan=False)        # -> decode oracle twin
        if split is None:
            out += sr.push(stream)
        else:
            out += sr.push(stream[:split])
            blob, drained = sr.checkpoint()
            out += drained
            sr = framebatch.StreamReceiver(
                sanitize=True, rejoin_after=2, checkpoint=blob,
                **GEO)
            assert sr._health.quarantined and sr._degraded
            out += sr.push(stream[split:])
        out += sr.flush()
        return out, sr.stats

    want, stats_c = run(None)
    got, stats_r = run(stream.shape[0] // 2)
    _same_frames(got, want)
    # the rejoined tail really decoded through the oracle twin, and
    # the quarantine dropped the head identically in both runs
    assert stats_r.degraded and stats_c.degraded
    assert stats_r.quarantines == stats_c.quarantines == 1
    assert len(want) < len(_rc[0])     # quarantine dropped something
    assert len(want) >= 1              # and the rejoin re-emitted


def test_cross_product_blob_restores_into_fleet_lane(corpus):
    """A quarantined+degraded session's blob restored into a FLEET
    lane (`restore_stream`, the serving runtime's recovery path): the
    quarantine rider restores per-lane, the degraded flags
    deliberately do NOT transfer (they describe the old runtime's
    compiled-program health; the degraded twin is bit-identical by
    the pinned contract, so emissions cannot diverge), and the
    lane-mate stays untouched."""
    _s, _st, _fc, streams, _fs, res_c = corpus
    stream = streams[0]
    cut = stream.shape[0] // 2

    def lone(split):
        sr = framebatch.StreamReceiver(sanitize=True, rejoin_after=2,
                                       **GEO)
        bad = np.zeros((16, 2), np.float32)
        bad[3] = np.nan
        out = sr.push(bad)
        sr._mark_degraded(scan=False)
        out += sr.push(stream[:split] if split else stream)
        return sr, out

    sr_c, want = lone(None)
    want += sr_c.flush()
    sr, first = lone(cut)
    blob, drained = sr.checkpoint()
    first += drained

    msr = framebatch.MultiStreamReceiver(2, sanitize=True,
                                         rejoin_after=2, **GEO)
    rest = msr.restore_stream(0, blob)
    assert msr._health[0].quarantined          # rider restored
    assert not msr._degraded and not msr._scan_degraded
    assert not msr._health[1].quarantined      # lane-mate untouched
    got2 = msr.push_many({0: stream[cut:], 1: streams[1]})
    got2 += msr.flush()
    rest += [f for i, f in got2 if i == 0]
    _same_frames(first + rest, want)
    # the healthy lane-mate is bit-identical to its clean fleet run
    _same_frames([f for i, f in got2 if i == 1], res_c[1])


def test_fleet_lane_checkpoint_restores_into_lone_receiver(corpus):
    _s, _st, _fc, streams, fstarts, res_c = corpus
    msr = framebatch.MultiStreamReceiver(4, **GEO)
    cut = streams[1].shape[0] // 2
    got = msr.push_many([s[:cut] for s in streams])
    state, drained = msr.checkpoint(1)
    got += drained
    first = [f for i, f in got if i == 1]
    sr = framebatch.StreamReceiver(checkpoint=state, **GEO)
    rest = sr.push(streams[1][cut:])
    rest += sr.flush()
    _same_frames(first + rest, res_c[1])


# --------------------------------------------------------------- CLI


def test_cli_chaos_flags_scope_env(tmp_path, monkeypatch):
    """--chaos / --max-retries write ZIRIA_CHAOS / ZIRIA_MAX_RETRIES
    for the invocation only (the scoped-env pattern): pre-existing
    values restore after main() returns."""
    import os

    from ziria_tpu.runtime.buffers import StreamSpec, write_stream
    from ziria_tpu.runtime.cli import build_parser, main as cli_main

    args = build_parser().parse_args(
        ["--chaos", "rx.push:nan_slab:every=2", "--max-retries", "4"])
    assert args.chaos == "rx.push:nan_slab:every=2"
    assert args.max_retries == 4

    inf, outf = tmp_path / "in.dbg", tmp_path / "out.dbg"
    rng = np.random.default_rng(0)
    write_stream(StreamSpec(ty="bit", path=str(inf), mode="dbg"),
                 rng.integers(0, 2, 16).astype(np.uint8))
    monkeypatch.setenv("ZIRIA_CHAOS", "keep:transient:every=9")
    monkeypatch.delenv("ZIRIA_MAX_RETRIES", raising=False)
    rc = cli_main([
        "--prog=scramble",
        "--input=file", f"--input-file-name={inf}",
        "--input-file-mode=dbg", "--input-type=bit",
        "--output=file", f"--output-file-name={outf}",
        "--output-file-mode=dbg", "--output-type=bit",
        "--backend=interp",
        "--chaos", "other:transient:every=3", "--max-retries", "1",
    ])
    assert rc == 0
    assert os.environ.get("ZIRIA_CHAOS") == "keep:transient:every=9"
    assert os.environ.get("ZIRIA_MAX_RETRIES") is None
    assert not faults.active()          # plan deactivated on exit
    # a malformed spec is a FLAG error at parse time, not a traceback
    # from deep inside the run
    with pytest.raises(SystemExit, match="--chaos"):
        cli_main(["--prog=scramble", "--chaos", "justasite"])
    with pytest.raises(SystemExit, match="--chaos"):
        cli_main(["--prog=scramble", "--chaos", "s:explode:every=2"])
