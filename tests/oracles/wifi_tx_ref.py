"""Independent numpy oracle for the 802.11a TX chain.

Written loop-style from the standard's block definitions, reusing the
per-op oracles (np_*_ref) — deliberately NOT sharing code with the jax
implementation it checks (golden-file pattern, SURVEY.md §4).
"""

import numpy as np

from ziria_tpu.ops.coding import np_conv_encode_ref, PUNCTURE_KEEP
from ziria_tpu.ops.interleave import np_interleave_ref
from ziria_tpu.ops.modulate import np_modulate_ref
from ziria_tpu.ops.scramble import np_scramble_ref
from ziria_tpu.phy.wifi.params import RATES, N_SERVICE_BITS, N_TAIL_BITS

PILOT_SC = [-21, -7, 7, 21]
DATA_SC = [k for k in range(-26, 27)
           if k != 0 and k not in PILOT_SC]


def pilot_polarity_ref():
    s = [1] * 7
    out = []
    for _ in range(127):
        fb = s[6] ^ s[3]
        out.append(1.0 if fb == 0 else -1.0)
        s = [fb] + s[:6]
    return out


def symbol_to_time_ref(data_syms, pilot_idx):
    """48 data symbols + pilot polarity index -> 80 time samples."""
    pol = pilot_polarity_ref()[pilot_idx % 127]
    bins = np.zeros(64, np.complex128)
    for sc, v in zip(DATA_SC, data_syms):
        bins[sc % 64] = v
    for sc, pv in zip(PILOT_SC, [1, 1, 1, -1]):
        bins[sc % 64] = pv * pol
    t = np.fft.ifft(bins) * 64 / np.sqrt(52.0)
    return np.concatenate([t[-16:], t])


def puncture_ref(coded, rate):
    keep = PUNCTURE_KEEP[rate]
    out = [b for i, b in enumerate(coded) if keep[i % keep.size]]
    return np.array(out, np.uint8)


def tx_frame_ref(psdu_bits, rate_mbps, seed_val=0b1011101):
    """Full frame: preamble + SIGNAL + DATA, complex128 samples."""
    rate = RATES[rate_mbps]
    length_bytes = len(psdu_bits) // 8
    n_bits = N_SERVICE_BITS + len(psdu_bits) + N_TAIL_BITS
    n_sym = -(-n_bits // rate.n_dbps)
    pad = n_sym * rate.n_dbps - n_bits

    raw = np.concatenate([np.zeros(N_SERVICE_BITS, np.uint8),
                          np.asarray(psdu_bits, np.uint8),
                          np.zeros(N_TAIL_BITS + pad, np.uint8)])
    seed = np.array([(seed_val >> k) & 1 for k in range(7)], np.uint8)
    scrambled = np_scramble_ref(raw, seed)
    tail_at = N_SERVICE_BITS + len(psdu_bits)
    scrambled[tail_at: tail_at + N_TAIL_BITS] = 0

    coded = puncture_ref(np_conv_encode_ref(scrambled), rate.coding)
    inter = np_interleave_ref(coded, rate.n_cbps, rate.n_bpsc)
    syms = np_modulate_ref(inter, rate.n_bpsc).reshape(n_sym, 48)
    data_t = np.concatenate(
        [symbol_to_time_ref(syms[s], 1 + s) for s in range(n_sym)])

    # SIGNAL
    rate_bits = [(rate.signal_bits >> k) & 1 for k in (3, 2, 1, 0)]
    length_bits = [(length_bytes >> k) & 1 for k in range(12)]
    head = rate_bits + [0] + length_bits
    sig = np.array(head + [sum(head) % 2] + [0] * 6, np.uint8)
    sig_coded = np_conv_encode_ref(sig)
    sig_inter = np_interleave_ref(sig_coded, 48, 1)
    sig_syms = np_modulate_ref(sig_inter, 1)
    sig_t = symbol_to_time_ref(sig_syms, 0)

    # preamble (same constants as the implementation; structure checked
    # separately in test_ops)
    from ziria_tpu.ops.ofdm import preamble
    p = np.asarray(preamble())  # pair format (320, 2)
    pre = p[..., 0] + 1j * p[..., 1]

    return np.concatenate([pre, sig_t, data_t])
