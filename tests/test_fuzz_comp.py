"""Seeded computation-level fuzzing: random `repeat { ... }` bodies
built from take/takes/emit/emits/do/static-for with stream-level
state, compiled by the full parser->elab path and required to agree
between the interpreter oracle and the fused jit backend (whose
firing functions trace these very bodies). Complements the
expression-level surface fuzzer."""

import numpy as np
import pytest

from ziria_tpu.backend.execute import run_jit
from ziria_tpu.frontend import compile_source
from ziria_tpu.interp.interp import run

N_CASES = 16


def _gen_body(rng):
    """One repeat-body: returns (lines, n_take). Always emits."""
    lines = []
    vals = []                      # scalar value names in scope
    arrs = []                      # (name, len) array values
    n_take = 0
    for _ in range(int(rng.integers(1, 5))):
        kind = rng.choice(["take", "takes", "do", "emit_for"])
        if kind == "take":
            v = f"x{len(vals)}"
            lines.append(f"  {v} <- take;")
            vals.append(v)
            n_take += 1
        elif kind == "takes":
            k = int(rng.choice([2, 4, 8]))
            a = f"v{len(arrs)}"
            lines.append(f"  ({a} : arr[{k}] int32) <- takes {k};")
            arrs.append((a, k))
            n_take += k
        elif kind == "do" and (vals or arrs):
            src = vals[-1] if vals and (not arrs or rng.random() < 0.5) \
                else f"{arrs[-1][0]}[{int(rng.integers(0, arrs[-1][1]))}]"
            lines.append(f"  do {{ s := s + {src} }};")
        elif kind == "emit_for" and arrs:
            a, k = arrs[int(rng.integers(0, len(arrs)))]
            lines.append(f"  for i in [0, {k}] {{ emit {a}[i] * 2 + s }};")
    # guaranteed stream input + emission
    if n_take == 0:
        lines.insert(0, "  x0 <- take;")
        vals.append("x0")
        n_take = 1
    src = vals[-1] if vals else f"{arrs[-1][0]}[0]"
    lines.append(f"  emit {src} + s;")
    lines.append("  do { s := s + 1 }")
    return lines, n_take


def _gen_program(seed):
    rng = np.random.default_rng(seed)
    body, n_take = _gen_body(rng)
    src = ("let comp main = read[int32] >>> {\n"
           "  var s : int32 := 0;\n"
           "  repeat {\n" + "\n".join("  " + ln for ln in body) +
           "\n  }\n} >>> write[int32]\n")
    # whole iterations only: the jit tail policy drops partial firings
    iters = int(rng.integers(3, 30))
    xs = rng.integers(-100, 100, iters * n_take).astype(np.int32)
    return src, xs


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_comp_backend_agreement(seed):
    src, xs = _gen_program(seed)
    prog = compile_source(src)
    want = np.asarray(run(prog.comp, list(xs)).out_array())
    got = np.asarray(run_jit(prog.comp, xs))
    np.testing.assert_array_equal(
        got, want, err_msg=f"seed {seed}\n{src}")
