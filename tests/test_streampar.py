"""Stream (sequence) parallelism over the 8-device virtual mesh
(parallel/streampar.py): one long stream split contiguously across
chips — stateless pipelines shard with no collectives; windowed ops
exchange a window-1 halo with one ppermute hop."""

import jax.numpy as jnp
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.parallel.streampar import (StreamParError, sliding_parallel,
                                          stream_mesh, stream_parallel)


def _mesh():
    return stream_mesh(8)


def test_stateless_pipeline_sharded_equals_single_chip():
    prog = z.pipe(z.zmap(lambda x: x * 3 + 1, name="affine"),
                  z.zmap(lambda x: x % 251, name="mod"))
    xs = np.arange(8 * 513, dtype=np.int32)       # uneven remainder
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rate_changing_stateless_pipeline():
    # takes 4 -> emit 1 (sum): iteration = 4 items; shards stay aligned
    prog = z.zmap(lambda v: jnp.sum(v), in_arity=4, out_arity=1,
                  name="sum4")
    xs = np.arange(8 * 64 * 4 + 12, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stateful_pipeline_refused():
    # data-dependent state (cumsum) has no valid fast-forward
    prog = z.map_accum(lambda s, x: (s + x, s + x), 0, name="cumsum")
    with pytest.raises(StreamParError, match="advance"):
        stream_parallel(prog, np.arange(64, dtype=np.int32), _mesh())


def test_advance_state_fast_forwarded():
    # counter state: s' = s + 1 per firing, out = x + s; advance is
    # closed-form s + n — classic scrambler/derotator shape. Exact
    # integer equality against the sequential single-chip run,
    # including the uneven tail.
    prog = z.pipe(
        z.zmap(lambda x: x * 2, name="pre"),
        z.map_accum(lambda s, x: (s + 1, x + s), 7, name="ctr",
                    advance=lambda s, n: s + n))
    xs = np.arange(8 * 300 + 13, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_memory_fir_fast_warmup():
    # 5-tap FIR whose state is the last 5 inputs (current included),
    # so memory=5; each device's entry state comes from a warmup scan
    # over the preceding items — exact integer equality with the
    # sequential run, uneven tail too
    import jax.numpy as jnp
    taps = np.array([1, -2, 3, -4, 5], np.int32)

    def fir_step(s, x):
        s2 = jnp.concatenate([s[1:], jnp.asarray(x, jnp.int32)[None]])
        y = jnp.sum(s2 * jnp.asarray(taps[::-1].copy()))
        return s2, y

    prog = z.map_accum(fir_step, np.zeros(5, np.int32), name="fir",
                       memory=5)
    xs = np.random.default_rng(7).integers(
        -50, 50, 8 * 200 + 11).astype(np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_memory_and_advance_mixed_pipeline():
    # map >>> counter(advance) >>> fir(memory): all three state classes
    # in one pipeline, sharded exactly
    import jax.numpy as jnp

    def fir_step(s, x):
        s2 = jnp.concatenate([s[1:], jnp.asarray(x, jnp.int32)[None]])
        return s2, jnp.sum(s2)

    prog = z.pipe(
        z.zmap(lambda x: x + 1, name="inc"),
        z.map_accum(lambda s, x: (s + 1, x * s), 1, name="ctr",
                    advance=lambda s, n: s + n),
        z.map_accum(fir_step, np.zeros(3, np.int32), name="fir3",
                    memory=3))
    xs = np.random.default_rng(8).integers(
        -9, 9, 8 * 150 + 5).astype(np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chained_memory_stages_cascade_warmup():
    # two FIR stages with a rate change between them: the downstream
    # delay line ingests the upstream's outputs, so warmups must ADD
    # (a max-based warmup fed it upstream cold-start values — found by
    # the executor-agreement fuzzer, seed 4)
    import jax.numpy as jnp

    def fir(k, name):
        def step(s, x):
            s2 = jnp.concatenate([s[1:],
                                  jnp.asarray(x, jnp.int32)[None]])
            return s2, jnp.sum(s2)
        return z.map_accum(step, np.zeros(k, np.int32), name=name,
                           memory=k)

    prog = z.pipe(fir(5, "a"),
                  z.zmap(lambda x: jnp.stack([x, -x]), in_arity=1,
                         out_arity=2, name="expand"),
                  fir(5, "b"))
    xs = np.random.default_rng(4).integers(
        -100, 100, 2427).astype(np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_memory_survives_fold():
    import jax.numpy as jnp

    def fir_step(s, x):
        s2 = jnp.concatenate([s[1:], jnp.asarray(x, jnp.int32)[None]])
        return s2, jnp.sum(s2)

    from ziria_tpu.core.opt import fold
    prog = fold(z.pipe(
        z.zmap(lambda x: x * 3, name="pre"),
        z.map_accum(fir_step, np.zeros(4, np.int32), name="fir4",
                    memory=4)))
    xs = np.arange(8 * 100 + 2, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_advance_survives_fold():
    # map-into-accum fusion must propagate the fast-forward: streampar
    # documents that stages shard "after fold"
    from ziria_tpu.core.opt import fold
    prog = fold(z.pipe(
        z.zmap(lambda x: x * 2, name="pre"),
        z.map_accum(lambda s, x: (s + 1, x + s), 7, name="ctr",
                    advance=lambda s, n: s + n)))
    xs = np.arange(8 * 64 + 3, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_advance_lfsr_scrambler_shape():
    # 3-bit LFSR advanced by matrix power over GF(2): the real
    # scrambler shape — state is a bit-vector, advance jumps n steps
    import jax.numpy as jnp

    M = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 0]], np.uint8)

    def step(s, x):
        out = x ^ s[0]
        return (jnp.asarray(M, jnp.uint8) @ s) % 2, out

    def mpow(n):
        r = np.eye(3, dtype=np.uint8)
        b = M.copy()
        while n:
            if n & 1:
                r = (r @ b) % 2
            b = (b @ b) % 2
            n >>= 1
        return r

    def advance(s, n):
        return (jnp.asarray(mpow(int(n)), jnp.uint8) @ s) % 2

    prog = z.map_accum(step, np.array([1, 0, 1], np.uint8),
                       name="lfsr", advance=advance)
    xs = np.random.default_rng(3).integers(
        0, 2, 8 * 100 + 5).astype(np.uint8)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_short_stream_runs_on_tail_path():
    prog = z.zmap(lambda x: x + 1, name="inc")
    xs = np.arange(5, dtype=np.int32)             # fewer than 8 devices
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), xs + 1)


def test_cli_sp_flag(tmp_path):
    # the driver's --sp=N shards the stream over N local devices and
    # must reproduce the single-device golden output exactly
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "sq.zir"
    src.write_text("""
      fun sq(x: int32) : int32 { return x * x }
      let comp main = read[int32] >>> map sq >>> write[int32]
    """)
    inf, out1, out8 = (tmp_path / n for n in ("in.dbg", "o1.dbg",
                                              "o8.dbg"))
    xs = np.arange(8 * 100 + 3, dtype=np.int32)
    inf.write_text(",".join(map(str, xs)))
    base = [f"--src={src}", "--input=file", f"--input-file-name={inf}",
            "--input-file-mode=dbg", "--output=file",
            "--output-file-mode=dbg"]
    assert cli_main(base + [f"--output-file-name={out1}"]) == 0
    assert cli_main(base + [f"--output-file-name={out8}", "--sp=8"]) == 0
    assert out1.read_text() == out8.read_text()


def test_cli_sp_flag_validation(tmp_path):
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "id.zir"
    src.write_text("""
      fun f(x: int32) : int32 { return x }
      let comp main = read[int32] >>> map f >>> write[int32]
    """)
    inf = tmp_path / "in.dbg"
    inf.write_text("1,2,3")
    base = [f"--src={src}", "--input=file", f"--input-file-name={inf}",
            "--input-file-mode=dbg", "--output=file",
            f"--output-file-name={tmp_path / 'o.dbg'}",
            "--output-file-mode=dbg"]
    with pytest.raises(SystemExit, match="at least 1"):
        cli_main(base + ["--sp=0"])
    with pytest.raises(SystemExit, match="needs --backend=jit"):
        cli_main(base + ["--sp=8", "--backend=hybrid"])
    with pytest.raises(SystemExit, match="--profile"):
        cli_main(base + ["--sp=8", "--profile"])


def test_cli_sp_refuses_stateful(tmp_path):
    from ziria_tpu.runtime.cli import main as cli_main
    src = tmp_path / "acc.zir"
    src.write_text("""
      let comp main = read[int32] >>> {
        var s : int32 := 0;
        repeat { x <- take; do { s := s + x }; emit s }
      } >>> write[int32]
    """)
    inf = tmp_path / "in.dbg"
    inf.write_text(",".join(map(str, range(64))))
    with pytest.raises(SystemExit, match="--sp=8"):
        cli_main([f"--src={src}", "--input=file",
                  f"--input-file-name={inf}", "--input-file-mode=dbg",
                  "--output=file",
                  f"--output-file-name={tmp_path / 'o.dbg'}",
                  "--output-file-mode=dbg", "--sp=8"])


def test_stream_parallel_batched_dp_x_sp():
    # 2x4 mesh: 6 frames over dp=2, each frame's items over sp=4;
    # stateless + advance stages; equals per-frame run_jit exactly
    import jax
    from ziria_tpu.parallel.streampar import stream_parallel_batched
    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2, 4),
                             ("dp", "sp"))
    prog = z.pipe(
        z.zmap(lambda x: x * 2 + 1, name="aff"),
        z.map_accum(lambda s, x: (s + 1, x + s), 3, name="ctr",
                    advance=lambda s, n: s + n))
    rng = np.random.default_rng(11)
    B, N = 6, 4 * 128
    batch = rng.integers(-50, 50, (B, N)).astype(np.int32)
    got = stream_parallel_batched(prog, batch, mesh)
    assert got.shape == (B, N)
    for f in range(B):
        want = run_jit(prog, batch[f])
        np.testing.assert_array_equal(got[f], np.asarray(want),
                                      err_msg=f"frame {f}")


def test_stream_parallel_batched_memory_per_frame_warmup():
    # finite-memory stages now join the batched path: each (frame,
    # shard) entry state is seeded from that FRAME's own preceding
    # items — exact equality with per-frame run_jit
    import jax
    import jax.numpy as jnp
    from ziria_tpu.parallel.streampar import stream_parallel_batched

    def fir_step(s, x):
        s2 = jnp.concatenate([s[1:], jnp.asarray(x, jnp.int32)[None]])
        return s2, jnp.sum(s2)

    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2, 4),
                             ("dp", "sp"))
    prog = z.pipe(
        z.zmap(lambda x: x * 2, name="pre"),
        z.map_accum(fir_step, np.zeros(3, np.int32), name="fir",
                    memory=3))
    rng = np.random.default_rng(17)
    batch = rng.integers(-40, 40, (4, 4 * 64)).astype(np.int32)
    got = stream_parallel_batched(prog, batch, mesh)
    for f in range(4):
        want = run_jit(prog, batch[f])
        np.testing.assert_array_equal(got[f], np.asarray(want),
                                      err_msg=f"frame {f}")


def test_stream_parallel_batched_refuses_raw_state():
    import jax
    from ziria_tpu.parallel.streampar import stream_parallel_batched

    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2, 4),
                             ("dp", "sp"))
    prog = z.map_accum(lambda s, x: (s + x, s + x), 0, name="cumsum")
    with pytest.raises(StreamParError, match="advance"):
        stream_parallel_batched(
            prog, np.zeros((2, 4 * 32), np.int32), mesh)


def test_sliding_parallel_matches_host():
    # correlation against a fixed 16-tap pattern: outs[i] =
    # sum(block[i:i+16] * taps)
    rng = np.random.default_rng(0)
    taps = jnp.asarray(rng.normal(size=16).astype(np.float32))
    xs = rng.normal(size=8 * 200).astype(np.float32)

    def corr(block, _t=taps):
        w = jnp.stack([block[i: i + block.shape[0] - 15]
                       for i in range(16)], axis=-1)
        return jnp.sum(w * _t[None, :], axis=-1)

    want = np.asarray(corr(jnp.asarray(xs)))
    got = sliding_parallel(corr, xs, window=16, mesh=_mesh())
    np.testing.assert_allclose(np.asarray(got), want,
                               rtol=1e-5, atol=1e-5)
    assert got.shape[0] == xs.shape[0] - 15


def test_sliding_window_one_is_plain_map():
    xs = np.arange(8 * 32, dtype=np.float32)
    got = sliding_parallel(lambda b: b * 2.0, xs, window=1, mesh=_mesh())
    np.testing.assert_array_equal(np.asarray(got), xs * 2.0)


def test_sliding_refuses_tiny_shards():
    xs = np.arange(16, dtype=np.float32)          # 2 items per device
    with pytest.raises(StreamParError, match="halo"):
        sliding_parallel(lambda b: b, xs, window=8, mesh=_mesh())


def test_rank_changing_output_sharded():
    # ADVICE r2 (medium): output items of LOWER rank than input items —
    # complex-pair (2,) in -> scalar magnitude out. The out_specs must
    # not be derived from the input rank.
    prog = z.zmap(lambda p: p[0] * p[0] + p[1] * p[1], name="mag2")
    rng = np.random.default_rng(3)
    xs = rng.integers(-50, 50, size=(8 * 129 + 5, 2)).astype(np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rank_increasing_output_sharded():
    # scalar in -> vector out (emit a (3,) item per input item)
    prog = z.zmap(lambda x: jnp.stack([x, x + 1, x * 2]), name="fan3")
    xs = np.arange(8 * 100, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_memory_stage_warmup_runs_on_device(monkeypatch):
    # VERDICT r2 weak #4: memory-stage entry states must come from the
    # in-shard_map ppermute halo, NOT host-side per-shard warmup scans.
    # Poison the host warmup closure: the device path never calls it.
    from ziria_tpu.parallel import streampar as SP

    def boom(*a, **k):
        raise AssertionError("host warmup path used")

    monkeypatch.setattr(SP, "_entry_carry_fn",
                        lambda *a, **k: boom)
    taps = np.array([1.0, -2.0, 3.0, 0.5], np.float32)

    def fir_step(state, x):
        state = jnp.concatenate([state[1:], x[None]])
        return state, jnp.sum(state * taps)

    prog = z.map_accum(fir_step, jnp.zeros(4, jnp.float32),
                       name="fir4", memory=4)
    xs = np.arange(8 * 64, dtype=np.float32)
    want = run_jit(prog, xs)
    got = SP.stream_parallel(prog, xs, _mesh())
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_batched_memory_stage_warmup_on_device(monkeypatch):
    from ziria_tpu.parallel import streampar as SP
    from ziria_tpu.parallel.streampar import stream_parallel_batched

    monkeypatch.setattr(
        SP, "_entry_carry_fn",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("host warmup path used")))
    taps = np.array([2.0, -1.0, 0.25], np.float32)

    def fir_step(state, x):
        state = jnp.concatenate([state[1:], x[None]])
        return state, jnp.sum(state * taps)

    prog = z.map_accum(fir_step, jnp.zeros(3, jnp.float32),
                       name="fir3", memory=3)
    rng = np.random.default_rng(11)
    B, N = 4, 4 * 128
    batch = rng.normal(size=(B, N)).astype(np.float32)
    import jax
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    got = stream_parallel_batched(prog, batch, mesh, width=32)
    for f in range(B):
        want = run_jit(prog, batch[f], width=32)
        np.testing.assert_allclose(np.asarray(got[f]),
                                   np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


def test_stream_parallel_batched_ragged_tail():
    # VERDICT r3 next #6: per-frame lengths NOT aligned to sp x width —
    # the aligned bulk runs on the 2-D mesh, the remaining iterations
    # finish per frame with the carry-seeded host tail; exact equality
    # with per-frame run_jit at several ragged lengths
    import jax
    from ziria_tpu.parallel.streampar import stream_parallel_batched
    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2, 4),
                             ("dp", "sp"))
    prog = z.pipe(
        z.zmap(lambda x: x * 2 + 1, name="aff"),
        z.map_accum(lambda s, x: (s + 1, x + s), 3, name="ctr",
                    advance=lambda s, n: s + n))
    rng = np.random.default_rng(23)
    for N in (4 * 128 + 1, 4 * 128 + 97, 513, 4 * 32 - 5):
        B = 4
        batch = rng.integers(-50, 50, (B, N)).astype(np.int32)
        got = stream_parallel_batched(prog, batch, mesh)
        for f in range(B):
            want = run_jit(prog, batch[f])
            np.testing.assert_array_equal(
                got[f], np.asarray(want), err_msg=f"N={N} frame {f}")


def test_stream_parallel_batched_memory_ragged():
    # ragged + finite-memory stage: tail carries seed from the frame's
    # own items at the bulk boundary
    import jax
    import jax.numpy as jnp
    from ziria_tpu.parallel.streampar import stream_parallel_batched

    def fir_step(s, x):
        s2 = jnp.concatenate([s[1:], jnp.asarray(x, jnp.int32)[None]])
        return s2, jnp.sum(s2)

    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2, 4),
                             ("dp", "sp"))
    prog = z.pipe(
        z.zmap(lambda x: x * 2, name="pre"),
        z.map_accum(fir_step, np.zeros(3, np.int32), name="fir",
                    memory=3))
    rng = np.random.default_rng(29)
    batch = rng.integers(-40, 40, (4, 4 * 64 + 37)).astype(np.int32)
    got = stream_parallel_batched(prog, batch, mesh)
    for f in range(4):
        want = run_jit(prog, batch[f])
        np.testing.assert_array_equal(got[f], np.asarray(want),
                                      err_msg=f"frame {f}")


def test_stream_parallel_batched_too_short_for_sp():
    # fewer steady-state iterations than sp devices: per == 0 path
    # runs every frame on the host — still exact, no error
    import jax
    from ziria_tpu.parallel.streampar import stream_parallel_batched
    devs = jax.devices()[:8]
    mesh = jax.sharding.Mesh(np.array(devs).reshape(2, 4),
                             ("dp", "sp"))
    prog = z.zmap(lambda x: x * 3 - 1, name="aff")
    batch = np.arange(2 * 3, dtype=np.int32).reshape(2, 3)
    got = stream_parallel_batched(prog, batch, mesh)
    for f in range(2):
        want = run_jit(prog, batch[f])
        np.testing.assert_array_equal(got[f], np.asarray(want))


def test_memory_window_spanning_shards_device_warm(monkeypatch):
    # r4 multi-hop warmup (closes VERDICT r3 weak #6): the memory
    # window is LARGER than one sp shard, so the warm window gathers
    # from several left neighbors; the host warmup must never run
    from ziria_tpu.parallel import streampar as SP

    def _no_host(*a, **k):
        raise AssertionError("host warmup path used")

    taps = np.arange(1, 41, dtype=np.int32) % 7 - 3     # 40-tap FIR

    def fir_step(state, x):
        state = jnp.concatenate([state[1:],
                                 jnp.asarray(x, jnp.int32)[None]])
        return state, jnp.sum(state * taps)

    prog = z.map_accum(fir_step, np.zeros(40, np.int32), name="fir40",
                       memory=40)
    # 8 sp devices x 16 iterations/shard = 128 total; window 40 spans
    # 3 shards (16-item shards)
    xs = (np.arange(8 * 16, dtype=np.int32) * 13) % 101
    want = run_jit(prog, xs)
    # the closure may be BUILT (the tail path shares it); host warmup
    # ran only if it is CALLED
    monkeypatch.setattr(SP, "_entry_carry_fn",
                        lambda *a, **k: _no_host)
    got = SP.stream_parallel(prog, xs, _mesh(), width=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_memory_window_longer_than_whole_prefix():
    # window even longer than (n_dev-1) shards: leading filler zeros
    # are masked for every device; exactness holds
    from ziria_tpu.parallel import streampar as SP

    def fir_step(state, x):
        state = jnp.concatenate([state[1:],
                                 jnp.asarray(x, jnp.int32)[None]])
        return state, jnp.sum(state)

    prog = z.map_accum(fir_step, np.zeros(100, np.int32),
                       name="fir100", memory=100)
    xs = (np.arange(8 * 13, dtype=np.int32) * 7) % 53
    want = run_jit(prog, xs)
    got = SP.stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_batched_memory_window_spanning_shards(monkeypatch):
    # dp x sp with a window wider than one sp shard: multi-hop gather
    # per frame, still no host warmup
    import jax
    from ziria_tpu.parallel import streampar as SP
    from ziria_tpu.parallel.streampar import stream_parallel_batched

    monkeypatch.setattr(
        SP, "_entry_carry_fn",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("host warmup path used")))
    taps = np.array([1, -2, 3, 1, -1, 2, 0, 1, -3, 2, 1, 1],
                    np.int32)

    def fir_step(state, x):
        state = jnp.concatenate([state[1:],
                                 jnp.asarray(x, jnp.int32)[None]])
        return state, jnp.sum(state * taps)

    prog = z.map_accum(fir_step, np.zeros(12, np.int32), name="fir12",
                       memory=12)
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("dp", "sp"))
    rng = np.random.default_rng(31)
    # 4 iterations/shard (width 2, 2 steps): window 12 spans 3 shards
    batch = rng.integers(-40, 40, (4, 4 * 8)).astype(np.int32)
    got = stream_parallel_batched(prog, batch, mesh, width=2)
    for f in range(4):
        want = run_jit(prog, batch[f], width=2)
        np.testing.assert_array_equal(got[f], np.asarray(want),
                                      err_msg=f"frame {f}")
