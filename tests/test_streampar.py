"""Stream (sequence) parallelism over the 8-device virtual mesh
(parallel/streampar.py): one long stream split contiguously across
chips — stateless pipelines shard with no collectives; windowed ops
exchange a window-1 halo with one ppermute hop."""

import jax.numpy as jnp
import numpy as np
import pytest

import ziria_tpu as z
from ziria_tpu.backend.execute import run_jit
from ziria_tpu.parallel.streampar import (StreamParError, sliding_parallel,
                                          stream_mesh, stream_parallel)


def _mesh():
    return stream_mesh(8)


def test_stateless_pipeline_sharded_equals_single_chip():
    prog = z.pipe(z.zmap(lambda x: x * 3 + 1, name="affine"),
                  z.zmap(lambda x: x % 251, name="mod"))
    xs = np.arange(8 * 513, dtype=np.int32)       # uneven remainder
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_rate_changing_stateless_pipeline():
    # takes 4 -> emit 1 (sum): iteration = 4 items; shards stay aligned
    prog = z.zmap(lambda v: jnp.sum(v), in_arity=4, out_arity=1,
                  name="sum4")
    xs = np.arange(8 * 64 * 4 + 12, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stateful_pipeline_refused():
    # data-dependent state (cumsum) has no valid fast-forward
    prog = z.map_accum(lambda s, x: (s + x, s + x), 0, name="cumsum")
    with pytest.raises(StreamParError, match="advance"):
        stream_parallel(prog, np.arange(64, dtype=np.int32), _mesh())


def test_advance_state_fast_forwarded():
    # counter state: s' = s + 1 per firing, out = x + s; advance is
    # closed-form s + n — classic scrambler/derotator shape. Exact
    # integer equality against the sequential single-chip run,
    # including the uneven tail.
    prog = z.pipe(
        z.zmap(lambda x: x * 2, name="pre"),
        z.map_accum(lambda s, x: (s + 1, x + s), 7, name="ctr",
                    advance=lambda s, n: s + n))
    xs = np.arange(8 * 300 + 13, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_advance_survives_fold():
    # map-into-accum fusion must propagate the fast-forward: streampar
    # documents that stages shard "after fold"
    from ziria_tpu.core.opt import fold
    prog = fold(z.pipe(
        z.zmap(lambda x: x * 2, name="pre"),
        z.map_accum(lambda s, x: (s + 1, x + s), 7, name="ctr",
                    advance=lambda s, n: s + n)))
    xs = np.arange(8 * 64 + 3, dtype=np.int32)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_advance_lfsr_scrambler_shape():
    # 3-bit LFSR advanced by matrix power over GF(2): the real
    # scrambler shape — state is a bit-vector, advance jumps n steps
    import jax.numpy as jnp

    M = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 0]], np.uint8)

    def step(s, x):
        out = x ^ s[0]
        return (jnp.asarray(M, jnp.uint8) @ s) % 2, out

    def mpow(n):
        r = np.eye(3, dtype=np.uint8)
        b = M.copy()
        while n:
            if n & 1:
                r = (r @ b) % 2
            b = (b @ b) % 2
            n >>= 1
        return r

    def advance(s, n):
        return (jnp.asarray(mpow(int(n)), jnp.uint8) @ s) % 2

    prog = z.map_accum(step, np.array([1, 0, 1], np.uint8),
                       name="lfsr", advance=advance)
    xs = np.random.default_rng(3).integers(
        0, 2, 8 * 100 + 5).astype(np.uint8)
    want = run_jit(prog, xs)
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_short_stream_runs_on_tail_path():
    prog = z.zmap(lambda x: x + 1, name="inc")
    xs = np.arange(5, dtype=np.int32)             # fewer than 8 devices
    got = stream_parallel(prog, xs, _mesh())
    np.testing.assert_array_equal(np.asarray(got), xs + 1)


def test_sliding_parallel_matches_host():
    # correlation against a fixed 16-tap pattern: outs[i] =
    # sum(block[i:i+16] * taps)
    rng = np.random.default_rng(0)
    taps = jnp.asarray(rng.normal(size=16).astype(np.float32))
    xs = rng.normal(size=8 * 200).astype(np.float32)

    def corr(block, _t=taps):
        w = jnp.stack([block[i: i + block.shape[0] - 15]
                       for i in range(16)], axis=-1)
        return jnp.sum(w * _t[None, :], axis=-1)

    want = np.asarray(corr(jnp.asarray(xs)))
    got = sliding_parallel(corr, xs, window=16, mesh=_mesh())
    np.testing.assert_allclose(np.asarray(got), want,
                               rtol=1e-5, atol=1e-5)
    assert got.shape[0] == xs.shape[0] - 15


def test_sliding_window_one_is_plain_map():
    xs = np.arange(8 * 32, dtype=np.float32)
    got = sliding_parallel(lambda b: b * 2.0, xs, window=1, mesh=_mesh())
    np.testing.assert_array_equal(np.asarray(got), xs * 2.0)


def test_sliding_refuses_tiny_shards():
    xs = np.arange(16, dtype=np.float32)          # 2 items per device
    with pytest.raises(StreamParError, match="halo"):
        sliding_parallel(lambda b: b, xs, window=8, mesh=_mesh())
