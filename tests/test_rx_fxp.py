"""Fixed-point RX interior (ops/fxp + phy/wifi/rx_fxp).

What the fixed-point path is FOR is reproducibility: every op is exact
int32 arithmetic, so outputs must be bit-identical across eager/jit and
across vmap widths — a stronger contract than the float path's
tolerance-bounded flag-independence (SURVEY.md §4's key invariant,
taken to equality). Plus numeric accuracy bounds for the primitives
and end-to-end agreement with the float receiver.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ziria_tpu.ops import fxp
from ziria_tpu.phy import channel
from ziria_tpu.phy.wifi import rx, rx_fxp, tx
from ziria_tpu.phy.wifi.params import RATES, n_symbols
from ziria_tpu.utils.bits import bytes_to_bits


# ------------------------------------------------------------ primitives

def test_isqrt_exact():
    rng = np.random.default_rng(0)
    x = np.concatenate([
        rng.integers(0, 2 ** 31 - 1, 3000),
        np.array([0, 1, 2, 3, 4, 2 ** 31 - 1, 2 ** 30, 65535, 65536])])
    got = np.asarray(fxp.isqrt_u32(jnp.asarray(x, jnp.int32)))
    want = np.floor(np.sqrt(x.astype(np.float64))).astype(np.int64)
    np.testing.assert_array_equal(got, want)


def test_cordic_atan2_accuracy():
    rng = np.random.default_rng(1)
    pts = (rng.normal(size=(4000, 2)) * 1e5).astype(np.int32)
    ang, mag = fxp.cordic_atan2(jnp.asarray(pts[:, 1]),
                                jnp.asarray(pts[:, 0]))
    ref = np.arctan2(pts[:, 1], pts[:, 0]) * (32768 / np.pi)
    d = (np.asarray(ang) - ref + 32768) % 65536 - 32768
    assert np.abs(d).max() <= 24          # ~0.13 degree
    mref = np.hypot(pts[:, 0], pts[:, 1]) * 1.646760258121
    ok = np.abs(np.asarray(mag) - mref) <= np.maximum(8, 2e-3 * mref)
    assert ok.all()


def test_cordic_atan2_axes_and_zero():
    y = jnp.asarray(np.array([0, 0, 5000, -5000, 0], np.int32))
    x = jnp.asarray(np.array([5000, -5000, 0, 0, 0], np.int32))
    ang, _ = fxp.cordic_atan2(y, x)
    ref = np.array([0, 32768, 16384, -16384, 0])
    d = (np.asarray(ang) - ref + 32768) % 65536 - 32768
    assert np.abs(d).max() <= 16


@pytest.mark.parametrize("kinv_bits,scale,tol_rel", [(15, 2e4, 2e-3),
                                                     (10, 3e5, 6e-3)])
def test_cordic_rotate_accuracy(kinv_bits, scale, tol_rel):
    rng = np.random.default_rng(2)
    v = (rng.normal(size=(4000, 2)) * scale).astype(np.int32)
    ang = rng.integers(-32768, 32768, 4000).astype(np.int32)
    got = np.asarray(fxp.cordic_rotate(jnp.asarray(v), jnp.asarray(ang),
                                       kinv_bits=kinv_bits))
    th = ang * np.pi / 32768
    want = np.stack([v[:, 0] * np.cos(th) - v[:, 1] * np.sin(th),
                     v[:, 0] * np.sin(th) + v[:, 1] * np.cos(th)], -1)
    err = np.hypot(*(got - want).T)
    assert (err <= np.maximum(16, tol_rel * np.hypot(v[:, 0], v[:, 1]))
            ).all()


def test_idft64_wifi_matches_ifft_timescale():
    """The inverse brick folds TIME_SCALE/64 = 1/sqrt(52) into its
    twiddles: integer bins at scale S -> time samples matching
    ifft * 64/sqrt(52) * S."""
    rng = np.random.default_rng(13)
    bins = (rng.normal(size=(4, 64, 2)) * 500).astype(np.int32)
    got = np.asarray(fxp.idft64_wifi_q14(jnp.asarray(bins)), np.float64)
    bc = bins[..., 0] + 1j * bins[..., 1]
    want = np.fft.ifft(bc, axis=-1) * 64.0 / np.sqrt(52.0)
    err = np.abs((got[..., 0] + 1j * got[..., 1]) - want)
    assert err.max() <= 2 + 2e-4 * np.abs(want).max()


def test_quantize_q_nonfinite_and_saturation():
    x = np.array([np.nan, np.inf, -np.inf, 100.0, -100.0, 0.4999e-3],
                 np.float32)
    got = np.asarray(fxp.quantize_q(x, 11))
    assert got[0] == 0 and got[1] == 32767 and got[2] == -32768
    assert got[3] == 32767 and got[4] == -32768   # saturated
    assert got[5] == 1                            # round-half-up


def test_dft64_matches_fft():
    rng = np.random.default_rng(3)
    x = (rng.normal(size=(5, 64, 2)) * 8000).astype(np.int32)
    got = np.asarray(fxp.dft64_q14(jnp.asarray(x), shift=7), np.float64)
    xc = x[..., 0] + 1j * x[..., 1]
    want = np.fft.fft(xc, axis=-1)
    err = np.abs((got[..., 0] + 1j * got[..., 1]) - want)
    # Q14 twiddle quantization over a 64-term sum
    assert err.max() <= 4 + 2e-4 * np.abs(want).max()


def test_primitives_bit_identical_jit_eager_vmap():
    rng = np.random.default_rng(4)
    v = (rng.normal(size=(64, 2)) * 2e4).astype(np.int32)
    a = rng.integers(-32768, 32768, 64).astype(np.int32)
    rot_e = fxp.cordic_rotate(jnp.asarray(v), jnp.asarray(a))
    rot_j = jax.jit(fxp.cordic_rotate)(jnp.asarray(v), jnp.asarray(a))
    rot_v = jax.vmap(fxp.cordic_rotate)(
        jnp.asarray(v.reshape(8, 8, 2)),
        jnp.asarray(a.reshape(8, 8))).reshape(64, 2)
    np.testing.assert_array_equal(np.asarray(rot_e), np.asarray(rot_j))
    np.testing.assert_array_equal(np.asarray(rot_e), np.asarray(rot_v))


# ----------------------------------------------------------- end to end

def _clean_case(mbps, n_bytes, seed):
    rate = RATES[mbps]
    rng = np.random.default_rng(seed)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    frame = np.asarray(tx.encode_frame(psdu, mbps))
    return rate, psdu, frame, n_symbols(n_bytes, rate)


@pytest.mark.parametrize("mbps", [6, 9, 12, 18, 24, 36, 48, 54])
def test_fxp_decodes_clean_frame_all_rates(mbps):
    rate, psdu, frame, n_sym = _clean_case(mbps, 120, seed=10 + mbps)
    fq = rx_fxp.quantize_frame(frame)
    got, _sv = rx_fxp.decode_data_fxp(fq, rate, n_sym, 8 * 120)
    np.testing.assert_array_equal(
        np.asarray(got), np.asarray(bytes_to_bits(psdu)))


def test_fxp_decodes_impaired_frame_like_float():
    # multipath + CFO + noise at operating SNR, frame pre-aligned by the
    # float sync (acquisition stays float; the fxp boundary is the
    # aligned frame) — fxp and float interiors must agree on the PSDU
    for mbps, seed in ((24, 71), (54, 72)):
        rate = RATES[mbps]
        n_bytes = 100
        psdu, cap = channel.impaired_capture(mbps, n_bytes, seed=seed)
        res = rx.receive(np.asarray(cap))
        assert res.ok
        want = np.asarray(bytes_to_bits(np.asarray(psdu, np.uint8)))
        # re-align exactly as receive() did, then hand the fxp path the
        # same aligned region. The capture is complex16 wire format at
        # scale 1024; the fxp boundary assumes unit average power
        # (AGC), so normalize before quantizing.
        x = np.asarray(cap, np.float32) / 1024.0
        found, start, eps = rx.sync_frame(jnp.asarray(x))
        assert bool(np.asarray(found))
        n_sym = n_symbols(n_bytes, rate)
        need = rx.FRAME_DATA_START + 80 * n_sym
        from ziria_tpu.ops import sync as sync_mod
        seg = sync_mod.correct_cfo(
            jnp.asarray(x[int(start): int(start) + need]),
            float(np.asarray(eps)))
        fq = rx_fxp.quantize_frame(seg)
        got, _sv = rx_fxp.decode_data_fxp(fq, rate, n_sym, 8 * n_bytes)
        np.testing.assert_array_equal(np.asarray(got), want)


@pytest.mark.slow
def test_fxp_bit_identical_across_jit_and_vmap_width():
    """The contract the module exists for: same quantized input ->
    bit-identical LLRs and bits, eager vs jit, batch of 1 vs batch of
    4, and batched rows vs per-frame runs. (tier-2: ~30s of
    per-geometry compiles; the clean/impaired e2e tests above keep
    the fxp interior covered in the tier-1 budget run)"""
    rate, psdu, frame, n_sym = _clean_case(24, 80, seed=30)
    noisy = frame + np.random.default_rng(31).normal(
        scale=0.05, size=frame.shape).astype(np.float32)
    fq = np.asarray(rx_fxp.quantize_frame(noisy))

    llr_e = np.asarray(rx_fxp.decode_front_fxp(
        jnp.asarray(fq), rate, n_sym))
    llr_j = np.asarray(jax.jit(
        lambda f: rx_fxp.decode_front_fxp(f, rate, n_sym))(
            jnp.asarray(fq)))
    np.testing.assert_array_equal(llr_e, llr_j)

    batch = np.stack([fq, fq, fq, fq])
    llr_b = np.asarray(jax.vmap(
        lambda f: rx_fxp.decode_front_fxp(f, rate, n_sym))(
            jnp.asarray(batch)))
    for row in llr_b:
        np.testing.assert_array_equal(row, llr_e)

    bits1, _ = rx_fxp.decode_data_fxp(jnp.asarray(fq), rate, n_sym,
                                      8 * 80)
    bitsb, _ = rx_fxp.decode_data_batch_fxp(jnp.asarray(batch), rate,
                                            n_sym, 8 * 80)
    for row in np.asarray(bitsb):
        np.testing.assert_array_equal(row, np.asarray(bits1))
    np.testing.assert_array_equal(np.asarray(bits1),
                                  np.asarray(bytes_to_bits(psdu)))


def test_receive_fxp_switch():
    """receive(fxp=True): the full host driver with the integer DATA
    interior — same PSDU as the float path on impaired captures,
    including FCS validation."""
    for mbps, seed in ((12, 81), (54, 82)):
        psdu, cap = channel.impaired_capture(mbps, 80, seed=seed,
                                             add_fcs=True)
        res_f = rx.receive(np.asarray(cap, np.float32), check_fcs=True)
        res_q = rx.receive(np.asarray(cap, np.float32), check_fcs=True,
                           fxp=True)
        assert res_f.ok and res_q.ok
        assert res_q.crc_ok and res_f.crc_ok
        assert res_q.rate_mbps == mbps
        np.testing.assert_array_equal(res_q.psdu_bits, res_f.psdu_bits)
        np.testing.assert_array_equal(
            res_q.psdu_bits[: 8 * 80],
            np.asarray(bytes_to_bits(np.asarray(psdu, np.uint8))))


@pytest.mark.slow
def test_fxp_ber_matches_float_at_operating_point():
    """Statistical agreement (the BER-waterfall suite's discipline
    applied to the integer interior): over a batch of AWGN frames at
    the 54 Mbps operating SNR, the fxp path's bit errors stay within
    a small absolute gap of the float path's (quantization loss only,
    no systematic degradation). (tier-2: a ~35s 16-frame statistical
    study — the point-wise fxp e2e tests above stay in tier-1)"""
    mbps, snr_db, n_frames, n_bytes = 54, 26.0, 16, 100
    rate = RATES[mbps]
    n_sym = n_symbols(n_bytes, rate)
    rng = np.random.default_rng(90)
    psdus = rng.integers(0, 256, (n_frames, n_bytes)).astype(np.uint8)
    frames = jnp.stack([tx.encode_frame(p, mbps) for p in psdus])
    key = jax.random.PRNGKey(91)
    noisy = jax.vmap(
        lambda k, f: channel.awgn(k, f, snr_db))(
            jax.random.split(key, n_frames), frames)
    want = np.stack([np.asarray(bytes_to_bits(p)) for p in psdus])

    got_f, _ = rx.decode_data_batch(noisy, rate, n_sym, 8 * n_bytes)
    ber_f = float(np.mean(np.asarray(got_f) != want))

    fq = jax.vmap(rx_fxp.quantize_frame)(noisy)
    got_q, _ = rx_fxp.decode_data_batch_fxp(fq, rate, n_sym,
                                            8 * n_bytes)
    ber_q = float(np.mean(np.asarray(got_q) != want))
    # operating point: float is (near-)clean; fxp may add only
    # quantization-level losses
    assert ber_f <= 1e-3
    assert ber_q <= ber_f + 2e-3, (ber_q, ber_f)


def test_fxp_llrs_track_float_llrs():
    """Directional sanity: fxp LLR signs agree with float LLRs on
    essentially every coded bit of a noisy frame (quantization may
    flip near-zero soft values only)."""
    rate, _psdu, frame, n_sym = _clean_case(54, 100, seed=40)
    noisy = frame + np.random.default_rng(41).normal(
        scale=0.03, size=frame.shape).astype(np.float32)
    dep_f = np.asarray(rx._decode_front(
        jnp.asarray(noisy, jnp.float32), rate, n_sym)).reshape(-1)
    dep_q = np.asarray(rx_fxp.decode_front_fxp(
        rx_fxp.quantize_frame(noisy), rate, n_sym),
        np.float64).reshape(-1)
    # compare where the float LLR is not tiny (true erasure positions
    # from depuncture are 0 in both)
    big = np.abs(dep_f) > 0.05 * np.abs(dep_f).max()
    agree = (np.sign(dep_f[big]) == np.sign(dep_q[big])).mean()
    assert agree > 0.999


@pytest.mark.slow
def test_batch_fxp_windowed_matches_exact():
    """viterbi_window on the integer batch path: same PSDU as the
    exact fxp decode on a long frame that genuinely windows (54 Mbps,
    200 bytes -> ~1650 trellis steps at window=512), preserving the
    integer front end untouched. (tier-2: ~55s — interpret-mode
    Pallas over a long trellis twice; the float windowed guard plus
    the fxp e2e tests cover the composition in tier-1)"""
    rate, psdu, frame, n_sym = _clean_case(54, 200, seed=33)
    noisy = frame + np.random.default_rng(34).normal(
        scale=0.03, size=frame.shape).astype(np.float32)
    fq = np.asarray(rx_fxp.quantize_frame(noisy))
    batch = jnp.asarray(np.stack([fq, fq]))
    exact, _ = rx_fxp.decode_data_batch_fxp(batch, rate, n_sym, 8 * 200)
    win, _ = rx_fxp.decode_data_batch_fxp(batch, rate, n_sym, 8 * 200,
                                          viterbi_window=512)
    np.testing.assert_array_equal(np.asarray(win), np.asarray(exact))
    np.testing.assert_array_equal(np.asarray(win[0]),
                                  np.asarray(bytes_to_bits(psdu)))
