"""Rate-SWITCHED fused decode (ISSUE 20): the mixed `lax.switch`
decode with demap + deinterleave + depuncture executed in-kernel from
one stacked all-rates slot-table bank.

Two contracts pinned here:

1. The constant bank itself (jax-free, no trace, no compile): every
   (rate, chunk) row of `mixed_front_tables()` must equal what the
   XLA front end's own primitives — `demap.demap_bit_layout`,
   `interleave.deinterleave_slots`, `coding.PUNCTURE_KEEP` — emit for
   those 24 depunctured slots, re-derived independently slot by slot.
   If demap or the interleaver ever changes, the bank pin fails
   before any kernel runs.

2. Lane-for-lane bit-identity of `decode_data_mixed(fused_demap=True)`
   vs the unfused mixed decode on an all-8-rates batch, over each
   lane's real bit prefix (past `n_bits_real` both paths decode
   zero-LLR erasures whose tie-broken bits carry no contract).

Budget discipline follows the known-rate fused tests
(test_viterbi_radix4): tier-1 compiles ONE mixed-fused kernel program
(the 8-symbol bucket, radix 2); the 16-symbol bucket class, the
radix-4 stack, and the quantized fallbacks ride the tier-2 ``slow``
marker. The end-to-end surface pins (receive_many / streaming /
fused link) live with their surfaces' own suites and the
`fused_mixed` bench stage.
"""

import numpy as np
import pytest

from ziria_tpu.ops import viterbi_pallas as vp

ALL_MBPS = (6, 9, 12, 18, 24, 36, 48, 54)


# ------------------------------------------------- bank pin (jax-free)


def test_mixed_bank_rows_pin_front_primitives():
    # independent slot-by-slot re-derivation — deliberately NOT via
    # _front_tables, so the bank is pinned to the primitives, not to
    # the code path that builds it
    from ziria_tpu.ops.coding import PUNCTURE_KEEP
    from ziria_tpu.ops.demap import demap_bit_layout
    from ziria_tpu.ops.interleave import deinterleave_slots
    from ziria_tpu.phy.wifi.params import RATE_MBPS_ORDER, RATES

    assert tuple(RATE_MBPS_ORDER) == ALL_MBPS
    bank_x, bank_l = vp.mixed_front_tables()
    assert bank_x.shape == (8, vp.MIXED_CHUNKS, 2 * vp.MIXED_SUB, 96)
    assert bank_l.shape == (8, vp.MIXED_CHUNKS, 2 * vp.MIXED_SUB, 8)

    for r, m in enumerate(RATE_MBPS_ORDER):
        rate = RATES[m]
        # the sub-block algebra the kernel relies on: every rate's
        # n_dbps is a multiple of MIXED_SUB, the bank is wide enough
        assert rate.n_dbps % vp.MIXED_SUB == 0
        cyc = rate.n_dbps // vp.MIXED_SUB
        assert cyc <= vp.MIXED_CHUNKS
        # chunks at/after the rate's cycle stay zero (never selected)
        assert not bank_x[r, cyc:].any()
        assert not bank_l[r, cyc:].any()

        keep = PUNCTURE_KEEP[rate.coding]
        period, kept = keep.size, int(keep.sum())
        nkeep_before = np.cumsum(keep) - keep
        sub, bit = deinterleave_slots(rate.n_cbps, rate.n_bpsc)
        comp, lev, amp = demap_bit_layout(rate.n_bpsc)
        for p in range(2 * rate.n_dbps):    # depunctured slot index
            c, row = divmod(p, 2 * vp.MIXED_SUB)
            ex = np.zeros(96, np.float32)
            el = np.zeros(8, np.float32)
            blk, off = divmod(p, period)
            if keep[off]:
                q = blk * kept + int(nkeep_before[off])
                sc, b = int(sub[q]), int(bit[q])
                ex[2 * sc + int(comp[b])] = 1.0
                el[int(lev[b])] = 1.0
                el[3] = float(amp[b])
                el[4] = 1.0        # depuncture validity
            np.testing.assert_array_equal(bank_x[r, c, row], ex,
                                          err_msg=f"rate {m} slot {p}")
            np.testing.assert_array_equal(bank_l[r, c, row], el,
                                          err_msg=f"rate {m} slot {p}")


def test_mixed_bank_matches_known_rate_tables():
    # the two fused fronts must share one table source: bank row r is
    # exactly the known-rate `_front_tables` split into 24-row chunks
    from ziria_tpu.phy.wifi.params import RATE_MBPS_ORDER, RATES

    bank_x, bank_l = vp.mixed_front_tables()
    for r, m in enumerate(RATE_MBPS_ORDER):
        rate = RATES[m]
        sel_x, _sel_g, lcols = vp._front_tables(
            rate.n_bpsc, rate.n_cbps, rate.n_dbps, rate.coding)
        cyc = rate.n_dbps // vp.MIXED_SUB
        t2 = 2 * vp.MIXED_SUB
        np.testing.assert_array_equal(
            bank_x[r, :cyc].reshape(cyc * t2, 96), sel_x)
        np.testing.assert_array_equal(
            bank_l[r, :cyc].reshape(cyc * t2, 8), lcols)


# --------------------------------------------- decode identity (compiled)


def _mixed_batch(n_bytes, seed, noise=0.03):
    """One noisy frame per rate, padded to the common symbol bucket —
    the shape decode_data_mixed takes on every fleet surface."""
    from ziria_tpu.phy.wifi import rx, tx
    from ziria_tpu.phy.wifi.params import RATES, n_symbols

    rng = np.random.default_rng(seed)
    n_sym_b = rx._sym_bucket(max(n_symbols(n_bytes, RATES[m])
                                 for m in ALL_MBPS))
    need = rx.FRAME_DATA_START + 80 * n_sym_b
    frames = np.zeros((len(ALL_MBPS), need, 2), np.float32)
    for i, m in enumerate(ALL_MBPS):
        psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
        s = np.asarray(tx.encode_frame(psdu, m))
        frames[i, :min(len(s), need)] = s[:min(len(s), need)]
    frames += rng.normal(0, noise, frames.shape).astype(np.float32)
    ridx = np.asarray([rx.RATE_INDEX[m] for m in ALL_MBPS], np.int32)
    nbits = np.asarray([n_symbols(n_bytes, RATES[m]) * RATES[m].n_dbps
                        for m in ALL_MBPS], np.int32)
    return frames, ridx, nbits, n_sym_b


def _assert_fused_identical(frames, ridx, nbits, n_sym_b, **kw):
    from ziria_tpu.phy.wifi import rx

    base = np.asarray(rx.decode_data_mixed(
        frames, ridx, nbits, n_sym_b, fused_demap=False, **kw))
    fused = np.asarray(rx.decode_data_mixed(
        frames, ridx, nbits, n_sym_b, fused_demap=True, **kw))
    mask = np.arange(base.shape[1])[None, :] < nbits[:, None]
    np.testing.assert_array_equal(fused[mask], base[mask])
    return base


def test_mixed_fused_bit_identical_all_rates_bucket8():
    # tier-1 pin: one batch with every rate, the 8-symbol bucket class
    # (the suite-shared streaming geometry), radix 2 — lane-for-lane
    # over each lane's real prefix
    frames, ridx, nbits, n_sym_b = _mixed_batch(12, seed=20)
    assert n_sym_b == 8
    _assert_fused_identical(frames, ridx, nbits, n_sym_b)


@pytest.mark.slow
def test_mixed_fused_bit_identical_bucket16_and_radix4():
    # the second spb class (16-symbol bucket) and the radix-4 stack —
    # two more interpret-mode kernel programs, minutes on CPU,
    # milliseconds of Mosaic compile on the chip
    frames, ridx, nbits, n_sym_b = _mixed_batch(24, seed=21)
    assert n_sym_b == 16
    _assert_fused_identical(frames, ridx, nbits, n_sym_b)
    _assert_fused_identical(frames, ridx, nbits, n_sym_b,
                            viterbi_radix=4)


@pytest.mark.slow
def test_mixed_fused_quantized_windowed_fall_back():
    # composition rule (same as the known-rate front): int16/int8 and
    # windowed decodes keep the unfused front — fused_demap=True must
    # be a no-op, so "identity" is exact program equality, int8's BER
    # envelope included by construction
    frames, ridx, nbits, n_sym_b = _mixed_batch(12, seed=22)
    for kw in ({"viterbi_metric": "int16"}, {"viterbi_metric": "int8"},
               {"viterbi_window": 512}):
        _assert_fused_identical(frames, ridx, nbits, n_sym_b, **kw)
