"""Stream-type checker: computer/transformer discipline (TcComp analogue)."""

import jax.numpy as jnp
import pytest

import ziria_tpu as z
from ziria_tpu.core import ir
from ziria_tpu.core.types import CTy, TTy, ZiriaTypeError, typecheck


def test_primitives_are_computers():
    assert isinstance(typecheck(z.take), CTy)
    assert isinstance(typecheck(z.takes(4)), CTy)
    assert isinstance(typecheck(z.emit1(1.0)), CTy)
    assert isinstance(typecheck(z.ret(0)), CTy)


def test_map_family_are_transformers():
    assert isinstance(typecheck(z.zmap(lambda x: x)), TTy)
    assert isinstance(typecheck(z.map_accum(lambda s, x: (s, x), 0)), TTy)


def test_repeat_of_computer_is_transformer():
    c = z.let("x", z.take, z.emit1(lambda e: e["x"]))
    assert isinstance(typecheck(c), CTy)
    assert isinstance(typecheck(z.repeat(c)), TTy)


def test_repeat_of_transformer_rejected():
    with pytest.raises(ZiriaTypeError, match="repeat needs a computer"):
        typecheck(z.repeat(z.zmap(lambda x: x)))


def test_bind_of_transformer_rejected():
    with pytest.raises(ZiriaTypeError, match="transformer"):
        typecheck(z.let("x", z.zmap(lambda x: x), z.emit1(0)))


def test_pipe_two_computers_rejected():
    with pytest.raises(ZiriaTypeError, match="control position"):
        typecheck(ir.Pipe(z.take, z.emit1(0)))


def test_pipe_computer_transformer_is_computer():
    # computer consuming the stream head, transformer downstream
    c = z.let("x", z.takes(3), z.emits(lambda e: e["x"], 3))
    t = z.zmap(lambda x: x * 2)
    assert isinstance(typecheck(ir.Pipe(c, t)), CTy)
    assert isinstance(typecheck(ir.Pipe(t, c)), CTy)
    assert isinstance(typecheck(ir.Pipe(t, t)), TTy)


def test_item_types_unified_through_pipe():
    t1, t2 = z.zmap(lambda x: x), z.zmap(lambda x: x)
    ty = typecheck(ir.Pipe(t1, t2))
    assert isinstance(ty, TTy)


def test_branch_kind_mismatch_rejected():
    with pytest.raises(ZiriaTypeError, match="arms disagree"):
        typecheck(z.branch(True, z.take, z.zmap(lambda x: x)))


def test_for_body_must_be_computer():
    ok = z.for_loop(4, z.let("x", z.take, z.emit1(lambda e: e["x"])))
    assert isinstance(typecheck(ok), CTy)
    with pytest.raises(ZiriaTypeError, match="for-loop body"):
        typecheck(z.for_loop(4, z.zmap(lambda x: x)))


def test_wifi_chains_typecheck():
    # the real 802.11a TX stream program must pass the checker
    from ziria_tpu.phy.wifi import tx
    prog = tx.tx_symbol_pipeline(36)
    ty = typecheck(prog)
    assert isinstance(ty, (CTy, TTy))
