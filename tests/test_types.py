"""Stream-type checker: computer/transformer discipline (TcComp analogue)."""

import jax.numpy as jnp
import pytest

import ziria_tpu as z
from ziria_tpu.core import ir
from ziria_tpu.core.types import CTy, TTy, ZiriaTypeError, typecheck


def test_primitives_are_computers():
    assert isinstance(typecheck(z.take), CTy)
    assert isinstance(typecheck(z.takes(4)), CTy)
    assert isinstance(typecheck(z.emit1(1.0)), CTy)
    assert isinstance(typecheck(z.ret(0)), CTy)


def test_map_family_are_transformers():
    assert isinstance(typecheck(z.zmap(lambda x: x)), TTy)
    assert isinstance(typecheck(z.map_accum(lambda s, x: (s, x), 0)), TTy)


def test_repeat_of_computer_is_transformer():
    c = z.let("x", z.take, z.emit1(lambda e: e["x"]))
    assert isinstance(typecheck(c), CTy)
    assert isinstance(typecheck(z.repeat(c)), TTy)


def test_repeat_of_transformer_rejected():
    with pytest.raises(ZiriaTypeError, match="repeat needs a computer"):
        typecheck(z.repeat(z.zmap(lambda x: x)))


def test_bind_of_transformer_rejected():
    with pytest.raises(ZiriaTypeError, match="transformer"):
        typecheck(z.let("x", z.zmap(lambda x: x), z.emit1(0)))


def test_pipe_two_computers_rejected():
    with pytest.raises(ZiriaTypeError, match="control position"):
        typecheck(ir.Pipe(z.take, z.emit1(0)))


def test_pipe_computer_transformer_is_computer():
    # computer consuming the stream head, transformer downstream
    c = z.let("x", z.takes(3), z.emits(lambda e: e["x"], 3))
    t = z.zmap(lambda x: x * 2)
    assert isinstance(typecheck(ir.Pipe(c, t)), CTy)
    assert isinstance(typecheck(ir.Pipe(t, c)), CTy)
    assert isinstance(typecheck(ir.Pipe(t, t)), TTy)


def test_item_types_unified_through_pipe():
    t1, t2 = z.zmap(lambda x: x), z.zmap(lambda x: x)
    ty = typecheck(ir.Pipe(t1, t2))
    assert isinstance(ty, TTy)


def test_branch_kind_mismatch_rejected():
    with pytest.raises(ZiriaTypeError, match="arms disagree"):
        typecheck(z.branch(True, z.take, z.zmap(lambda x: x)))


def test_for_body_must_be_computer():
    ok = z.for_loop(4, z.let("x", z.take, z.emit1(lambda e: e["x"])))
    assert isinstance(typecheck(ok), CTy)
    with pytest.raises(ZiriaTypeError, match="for-loop body"):
        typecheck(z.for_loop(4, z.zmap(lambda x: x)))


def test_wifi_chains_typecheck():
    # the real 802.11a TX stream program must pass the checker
    from ziria_tpu.phy.wifi import tx
    prog = tx.tx_symbol_pipeline(36)
    ty = typecheck(prog)
    assert isinstance(ty, (CTy, TTy))


# ------------------------------------------------ item-dtype unification


def test_pipe_dtype_conflict_rejected():
    """A complex-producing stage feeding a real-consuming stage is a
    stream type error (VERDICT r1 weak #6 — previously two opaque
    TVars unified silently)."""
    import pytest

    import ziria_tpu as z
    from ziria_tpu.core.types import ZiriaTypeError, typecheck

    good = z.pipe(z.zmap(lambda x: x, out_dtype="complex64"),
                  z.zmap(lambda x: x, in_dtype="complex64"))
    typecheck(good)

    bad = z.pipe(z.zmap(lambda x: x, out_dtype="uint8"),
                 z.zmap(lambda x: x, in_dtype="complex64"))
    with pytest.raises(ZiriaTypeError, match="dtype mismatch"):
        typecheck(bad)


def test_pipe_dtype_widths_compatible():
    """Width changes are legal implicit casts — int16 feeding int32
    must NOT error (only the complex/real boundary is hard)."""
    import ziria_tpu as z
    from ziria_tpu.core.types import typecheck

    typecheck(z.pipe(z.zmap(lambda x: x, out_dtype="int16"),
                     z.zmap(lambda x: x, in_dtype="int32")))
    typecheck(z.pipe(z.zmap(lambda x: x, out_dtype="float32"),
                     z.zmap(lambda x: x, in_dtype="int32")))


def test_dtype_flows_through_branch_unification():
    """Dtypes propagate along unification chains: branch arms unify, so
    a complex-consuming arm and a bit-consuming arm conflict."""
    import pytest

    import ziria_tpu as z
    from ziria_tpu.core.types import ZiriaTypeError, typecheck

    bad = z.branch(lambda env: True,
                   z.zmap(lambda x: x, in_dtype="complex64"),
                   z.zmap(lambda x: x, in_dtype="uint8"))
    with pytest.raises(ZiriaTypeError, match="dtype mismatch"):
        typecheck(bad)


def test_surface_dtype_conflict_from_signatures():
    """Ext signatures carry dtypes into the IR, and build() runs the
    stream typechecker: a complex-typed map feeding a bit-typed map is
    rejected at compile time through compile_source itself."""
    import pytest

    from ziria_tpu.frontend import ElabError, compile_source

    src = """
      ext fun conj(x: complex16) : complex16
      fun tobit(x: bit) : bit { return x }
      let comp main = read[complex16] >>> map conj >>> map tobit
                      >>> write[bit]
    """
    with pytest.raises(ElabError, match="dtype mismatch"):
        compile_source(src)
