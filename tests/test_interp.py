"""Interpreter semantics tests: the combinator laws of the stream level.

These pin the oracle's behavior (take/emit/map/repeat/bind/pipe and the
termination rules of `>>>`), mirroring the reference's language-level test
group (SURVEY.md §4)."""

import numpy as np
import pytest

from ziria_tpu import (take, takes, emit1, emits, ret, seq, let, zmap,
                       map_accum, repeat, pipe, par_pipe, for_loop,
                       while_loop, branch)
from ziria_tpu.core.ir import let_ref, assign
from ziria_tpu.interp.interp import run
from ziria_tpu.utils.diff import assert_stream_eq


def test_take_returns_item():
    r = run(take, [42, 7])
    assert r.value == 42
    assert r.outputs == []
    assert r.consumed == 1
    assert r.terminated_by == "computer"


def test_takes_stacks():
    r = run(takes(3), [1, 2, 3, 4])
    assert_stream_eq(r.value, np.array([1, 2, 3]))


def test_emit_value():
    r = run(emit1(5), [])
    assert r.outputs == [5]
    assert r.value is None


def test_emits_array():
    r = run(emits(np.array([1, 2, 3]), 3), [])
    assert [int(x) for x in r.outputs] == [1, 2, 3]


def test_bind_passes_value():
    c = let("x", take, emit1(lambda env: env["x"] * 10))
    r = run(c, [7])
    assert r.outputs == [70]


def test_seq_discards():
    c = seq(emit1(1), emit1(2), ret(99))
    r = run(c, [])
    assert r.outputs == [1, 2]
    assert r.value == 99


def test_map_doubles_forever_until_eof():
    c = zmap(lambda x: x * 2)
    r = run(c, [1, 2, 3])
    assert [int(x) for x in r.outputs] == [2, 4, 6]
    assert r.terminated_by == "eof"


def test_map_chunked_arity():
    # takes 2 items, emits their sum then difference (2 -> 2 chunk map)
    c = zmap(lambda v: np.array([v[0] + v[1], v[0] - v[1]]),
             in_arity=2, out_arity=2)
    r = run(c, [5, 3, 10, 4])
    assert [int(x) for x in r.outputs] == [8, 2, 14, 6]


def test_map_accum_running_sum():
    c = map_accum(lambda s, x: (s + x, s + x), 0)
    r = run(c, [1, 2, 3, 4])
    assert [int(x) for x in r.outputs] == [1, 3, 6, 10]


def test_repeat_of_computer():
    # repeat { x <- take; emit x+1 }
    c = repeat(let("x", take, emit1(lambda env: env["x"] + 1)))
    r = run(c, [10, 20, 30])
    assert [int(x) for x in r.outputs] == [11, 21, 31]


def test_pipe_transformers():
    c = pipe(zmap(lambda x: x + 1), zmap(lambda x: x * 3))
    r = run(c, [0, 1, 2])
    assert [int(x) for x in r.outputs] == [3, 6, 9]


def test_pipe_downstream_computer_terminates_first():
    # infinite upstream, downstream takes 2 then returns their sum
    c = pipe(zmap(lambda x: x * 2),
             let("v", takes(2), ret(lambda env: env["v"].sum())))
    r = run(c, [1, 2, 3, 4, 5])
    assert r.value == 6  # (1*2) + (2*2)
    assert r.terminated_by == "computer"
    assert r.consumed == 2


def test_pipe_upstream_computer_terminates_first():
    # upstream emits 2 then returns "done"; downstream maps forever
    up = seq(emit1(1), emit1(2), ret("done"))
    c = pipe(up, zmap(lambda x: x + 100))
    r = run(c, [])
    assert [int(x) for x in r.outputs] == [101, 102]
    assert r.value == "done"
    # the pipe terminates *locally* with the upstream's value — a normal
    # computer termination, not an EOF abort of the whole program
    assert r.terminated_by == "computer"


def test_bind_continues_after_pipe_upstream_terminates():
    # v <- (emit 1; return 5) >>> map(+100) ; emit v*2
    # The pipe terminates with 5; the enclosing bind must keep running.
    c = let("v", pipe(seq(emit1(1), ret(5)), zmap(lambda x: x + 100)),
            emit1(lambda env: env["v"] * 2))
    r = run(c, [])
    assert [int(x) for x in r.outputs] == [101, 10]
    assert r.terminated_by == "computer"


def test_outer_eof_still_propagates_through_nested_pipes():
    c = pipe(zmap(lambda x: x + 1), pipe(zmap(lambda x: x * 2),
                                         zmap(lambda x: x - 3)))
    r = run(c, [1, 2])
    assert [int(x) for x in r.outputs] == [1, 3]
    assert r.terminated_by == "eof"


def test_repeat_of_pure_computer_rejected():
    with pytest.raises(ValueError, match="diverges"):
        run(repeat(ret(0)), [], max_out=5)


def test_assign_to_let_binding_rejected():
    c = let("x", take, seq(assign("x", 99), emit1(lambda env: env["x"])))
    with pytest.raises(KeyError, match="immutable let-binding"):
        run(c, [1])


def test_emits_scalar_rejected():
    with pytest.raises(ValueError, match="emits"):
        run(emits(5, 1), [])


def test_par_pipe_identical_to_pipe():
    # |>>>| must produce output identical to >>> (reference invariant)
    a = pipe(zmap(lambda x: x + 1), zmap(lambda x: x * 3))
    b = par_pipe(zmap(lambda x: x + 1), zmap(lambda x: x * 3))
    xs = list(range(10))
    assert_stream_eq(run(a, xs).out_array(), run(b, xs).out_array())


def test_for_loop_emits():
    c = for_loop(4, emit1(lambda env: env["i"] ** 2), var="i")
    r = run(c, [])
    assert [int(x) for x in r.outputs] == [0, 1, 4, 9]


def test_while_with_ref():
    # var n := 0; while n < 3 { emit n; n := n + 1 }
    c = let_ref(
        "n", 0,
        while_loop(lambda env: env["n"] < 3,
                   seq(emit1(lambda env: env["n"]),
                       assign("n", lambda env: env["n"] + 1))))
    r = run(c, [])
    assert [int(x) for x in r.outputs] == [0, 1, 2]


def test_branch():
    c = let("x", take,
            branch(lambda env: env["x"] > 0, emit1("pos"), emit1("neg")))
    assert run(c, [5]).outputs == ["pos"]
    assert run(c, [-5]).outputs == ["neg"]


def test_rate_mismatch_pipe():
    # up emits chunks of 3; down consumes chunks of 2 -> item streams still align
    up = zmap(lambda v: v * 2, in_arity=3, out_arity=3)
    down = zmap(lambda v: v.sum(), in_arity=2, out_arity=1)
    r = run(pipe(up, down), [1, 2, 3, 4, 5, 6])
    # doubled: 2,4,6,8,10,12 ; pairs: (2+4),(6+8),(10+12)
    assert [int(x) for x in r.outputs] == [6, 14, 22]


def test_max_out_limit():
    c = repeat(emit1(1))
    r = run(c, [], max_out=5)
    assert len(r.outputs) == 5
    assert r.terminated_by == "limit"


def test_repeat_dynamic_pure_body_detected_at_runtime():
    # For with a dynamic count of 0: cardinality is DYN, so only the
    # runtime progress guard can catch the divergence.
    c = repeat(for_loop(lambda env: 0, emit1(1)))
    with pytest.raises(ValueError, match="no stream progress"):
        run(c, [], max_out=1)


def test_max_out_zero():
    r = run(repeat(emit1(1)), [], max_out=0)
    assert r.outputs == []
    assert r.terminated_by == "limit"
