"""One-dispatch mixed-rate TX (tx.encode_many) and the device-resident
loopback link (phy/link.py): an N-frame batch of mixed rates AND
lengths encodes in ONE vmapped lax.switch dispatch, bit-identical lane
for lane to per-frame `encode_frame`, and the full TX -> channel -> RX
loopback runs in <= 5 device dispatches vs >= N for the per-frame
oracle loop — with identical RxResults either way.

Budget discipline (the tier-1 870 s cutoff is real): ONE module
fixture pays the expensive geometry compiles — 8 lanes, 128-bit bit
bucket, 8-symbol bucket (the decode geometry test_rx_mixed_dispatch /
test_rx_batched_acquire already compile, shared through the
process-wide jit caches) — and every test re-dispatches those
compiled graphs. Dispatch counts come from the instrumented
utils/dispatch.count_dispatches counter; compile counts from
utils/dispatch.cache_growth (lru deltas, never cache_clear).
"""

import numpy as np
import pytest

from ziria_tpu.phy import channel, link
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import (RATE_INDEX, RATE_MBPS_ORDER,
                                       RATES)
from ziria_tpu.utils import dispatch
from ziria_tpu.utils.bits import bytes_to_bits, np_bytes_to_bits

# all 8 rates with MIXED lengths in one batch; the 16-byte 6 Mbps lane
# pins the common symbol bucket at 8 (the suite-shared decode
# geometry), lengths stay inside the 128-bit bit bucket
LENS = (16, 10, 16, 5, 16, 12, 9, 16)
MBPS = tuple(sorted(RATES))
CFO = tuple((-1) ** k * 1e-4 * (k + 1) for k in range(8))
DELAY = tuple(20 + 17 * k for k in range(8))
SEED = 20260803


@pytest.fixture(scope="module")
def corpus():
    """PSDUs + one batched and one per-frame loopback pass (noise-free
    channel with per-lane CFO + delay), each under a dispatch
    counter. The batched pass pins ``fused=False`` throughout this
    file: it is the STAGED-vs-perframe contract; the fused one-
    dispatch graph is judged against the staged path in
    tests/test_link_fused.py."""
    rng = np.random.default_rng(SEED)
    psdus = [rng.integers(0, 256, n).astype(np.uint8) for n in LENS]
    with dispatch.count_dispatches() as d_bat:
        got_b = link.loopback_many(psdus, MBPS, snr_db=np.inf, cfo=CFO,
                                   delay=DELAY, seed=3, batched_tx=True,
                                   fused=False)
    with dispatch.count_dispatches() as d_pf:
        got_f = link.loopback_many(psdus, MBPS, snr_db=np.inf, cfo=CFO,
                                   delay=DELAY, seed=3,
                                   batched_tx=False)
    return psdus, got_b, got_f, d_bat, d_pf


def _same_result(a, b) -> bool:
    return (a.ok == b.ok and a.rate_mbps == b.rate_mbps
            and a.length_bytes == b.length_bytes
            and np.array_equal(a.psdu_bits, b.psdu_bits)
            and a.crc_ok == b.crc_ok)


def test_encode_many_bit_identical_all_rates_mixed_lengths(corpus):
    # the acceptance contract: lane for lane bit-identical to
    # per-frame encode_frame across ALL 8 rates with MIXED lengths in
    # the same batch, valid counts exact
    psdus, _gb, _gf, _db, _dp = corpus
    txb = tx.encode_many(psdus, MBPS)
    arr = np.asarray(txb.samples)
    for i, (p, m) in enumerate(zip(psdus, MBPS)):
        want = np.asarray(tx.encode_frame(p, m))
        assert txb.n_valid[i] == want.shape[0]
        np.testing.assert_array_equal(arr[i, :txb.n_valid[i]], want)
        # pad region is garbage symbols, never silently part of a frame
        assert txb.n_sym_bucket * 80 + 400 == arr.shape[1]


def test_encode_frame_jit_path_equals_eager_graph():
    # encode_frame's cached-jit dispatch vs the untraced oracle graph
    # (encode_frame_bits, itself pinned to the numpy oracle by
    # test_wifi_tx) — the single-frame half of the bit-identity story
    rng = np.random.default_rng(5)
    for m, nb in ((6, 16), (54, 9)):
        psdu = rng.integers(0, 256, nb).astype(np.uint8)
        want = np.asarray(tx.encode_frame_bits(
            bytes_to_bits(np.asarray(psdu), xp=np), RATES[m]))
        np.testing.assert_array_equal(
            np.asarray(tx.encode_frame(psdu, m)), want)


def test_loopback_batched_equals_perframe_oracle(corpus):
    psdus, got_b, got_f, _db, _dp = corpus
    assert len(got_b) == len(psdus)
    for a, b, p, m in zip(got_b, got_f, psdus, MBPS):
        assert a.ok and a.rate_mbps == m
        np.testing.assert_array_equal(a.psdu_bits, np_bytes_to_bits(p))
        assert _same_result(a, b)


def test_loopback_dispatch_counts(corpus):
    # the tentpole number: encode + channel + acquire + gather + mixed
    # decode = 5 dispatches for the whole mixed-rate batch, vs >= N
    # (here >= 5N: encode, impair, sync, SIGNAL, decode per frame) for
    # the per-frame path
    _psdus, _gb, _gf, d_bat, d_pf = corpus
    n = len(LENS)
    assert d_bat.total <= 5, dict(d_bat.counts)
    for site in ("tx.encode_many", "channel.impair_many",
                 "rx.acquire_many", "rx.gather", "rx.decode_mixed"):
        assert d_bat.counts[site] == 1, dict(d_bat.counts)
    assert d_pf.total >= n, dict(d_pf.counts)
    assert d_pf.counts["tx.encode_frame"] == n
    assert d_pf.counts["channel.impair"] == n


def test_loopback_dispatches_constant_in_batch_size(corpus):
    # O(1) means O(1): 7 lanes pad back to the fixture's 8-lane
    # geometry — same five dispatches, zero fresh compiles, results
    # still exact (keep lane 0: its 6 Mbps 16-byte frame pins the
    # shared 8-symbol decode bucket)
    psdus, got_b, _gf, _db, _dp = corpus
    with dispatch.cache_growth(tx._jit_encode_many,
                               channel._jit_impair_many,
                               rx._jit_decode_data_mixed) as g, \
            dispatch.count_dispatches() as d:
        got = link.loopback_many(psdus[:7], MBPS[:7], snr_db=np.inf,
                                 cfo=CFO[:7], delay=DELAY[:7], seed=3,
                                 batched_tx=True, fused=False)
    assert d.total <= 5
    assert g.total == 0
    for a, b in zip(got, got_b[:7]):
        assert _same_result(a, b)


def test_noisy_and_failed_lanes_match_perframe(corpus):
    # real AWGN at per-lane SNRs, one lane swamped (-25 dB): the
    # batched link classifies and decodes every lane exactly as the
    # per-frame loop — including the failure — at the fixture's
    # compiled geometry
    psdus, _gb, _gf, _db, _dp = corpus
    snrs = [25.0, 30.0, -25.0, 28.0, 25.0, 30.0, 27.0, 26.0]
    got_b = link.loopback_many(psdus, MBPS, snr_db=snrs, cfo=CFO,
                               delay=DELAY, seed=11, batched_tx=True,
                               fused=False)
    got_f = link.loopback_many(psdus, MBPS, snr_db=snrs, cfo=CFO,
                               delay=DELAY, seed=11, batched_tx=False)
    for a, b in zip(got_b, got_f):
        assert _same_result(a, b)
    assert not got_b[2].ok          # the swamped lane really failed
    assert got_b[0].ok and got_b[7].ok


def test_channel_batched_equals_oracle_samplewise(corpus):
    """The pre-Viterbi channel gate: at FINITE SNR with mixed symbol
    buckets — short lanes carry garbage bucket-pad symbols past
    n_valid, exactly the region impair_graph must mask — every capture
    sample of the batched channel equals the per-frame oracle bit for
    bit. The decode-level identity tests cannot see a channel
    divergence the Viterbi corrects (wrong delivered SNR, perturbed
    noise scaling); this one can."""
    psdus, _gb, _gf, _db, _dp = corpus
    txb = tx.encode_many(psdus, MBPS)
    assert (txb.n_valid < txb.samples.shape[1]).any()   # pads exist
    l_cap = rx._stream_bucket(int(txb.samples.shape[1]) + max(DELAY))
    snrs = np.asarray([25.0 + k for k in range(8)], np.float32)
    caps = np.asarray(channel.impair_many(
        txb.samples, txb.n_valid, snrs, np.asarray(CFO, np.float32),
        np.asarray(DELAY, np.int32), seed=13, out_len=l_cap))
    for i, (p, m) in enumerate(zip(psdus, MBPS)):
        s = np.asarray(tx.encode_frame(p, m))
        want = np.asarray(channel.impair_one(
            s, snrs[i], CFO[i], DELAY[i], 13, i, l_cap))
        np.testing.assert_array_equal(caps[i], want)


def test_compile_count_o_log_buckets_not_o_lengths():
    # the cache-growth SHAPE contract: many (rate, length) combos, few
    # compiled encoders. 6 lengths spanning ONE bit bucket and one
    # symbol bucket per rate -> encode_frame grows O(buckets) entries
    # (<= 2 per rate here), never one per length; a second encode_many
    # batch at new lengths inside the fixture geometry grows NOTHING.
    rng = np.random.default_rng(9)
    lens = (5, 6, 7, 9, 11, 13)
    with dispatch.cache_growth(tx._jit_encode_frame) as g:
        for m in (12, 48):
            for nb in lens:
                tx.encode_frame(rng.integers(0, 256, nb).astype(np.uint8),
                                m)
    # 2 rates x (1 bit bucket x <= 2 symbol buckets) — not 2 x 6
    assert g[tx._jit_encode_frame] <= 4, g.growth

    psdus = [rng.integers(0, 256, n).astype(np.uint8)
             for n in (14, 8, 13, 7, 11, 6, 5, 10)]
    with dispatch.cache_growth(tx._jit_encode_many) as g2:
        txb = tx.encode_many(psdus, MBPS)
    assert g2.total == 0, "new lengths in an old geometry re-compiled"
    for i, (p, m) in enumerate(zip(psdus, MBPS)):
        np.testing.assert_array_equal(
            np.asarray(txb.samples[i, :txb.n_valid[i]]),
            np.asarray(tx.encode_frame(p, m)))


def test_transmit_many_matches_perframe(corpus):
    psdus, _gb, _gf, _db, _dp = corpus
    from ziria_tpu.backend import framebatch
    with dispatch.count_dispatches() as d:
        got = framebatch.transmit_many(psdus, MBPS, batched_tx=True)
    assert d.counts["tx.encode_many"] == 1 and d.total == 1
    ref = framebatch.transmit_many(psdus, MBPS, batched_tx=False)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)


def test_batched_tx_env_knob(monkeypatch):
    # the CLI's scoped-env pattern: default ON, ZIRIA_BATCHED_TX=0
    # forces the oracle loop, an explicit argument wins over the env
    monkeypatch.delenv("ZIRIA_BATCHED_TX", raising=False)
    assert link.batched_tx_enabled(None)
    monkeypatch.setenv("ZIRIA_BATCHED_TX", "0")
    assert not link.batched_tx_enabled(None)
    assert link.batched_tx_enabled(True)
    monkeypatch.setenv("ZIRIA_BATCHED_TX", "1")
    assert link.batched_tx_enabled(None)
    assert not link.batched_tx_enabled(False)


def test_tx_rx_bucket_rules_agree():
    # encode_many buckets symbol counts with tx._sym_bucket; the mixed
    # decode buckets with rx._sym_bucket — the loopback's geometry
    # contract is that they are the SAME rule (both call
    # utils/dispatch.pow2_bucket); a drift would silently double
    # compile classes
    for k in range(1, 200):
        assert tx._sym_bucket(k) == rx._sym_bucket(k)
    # and the switch order TX encodes with is the one RX decodes with
    assert tuple(RATE_MBPS_ORDER) == rx.RATE_MBPS_ORDER
    for m, i in RATE_INDEX.items():
        assert rx.RATE_INDEX[m] == i
