"""Compiled-program observatory (ziria_tpu/utils/programs.py): XLA
cost/memory attribution per jit-factory program, CPU-only (ISSUE 9).

Budget discipline: ONE module fixture drives the receive / batched /
streaming surfaces at the suite's shared tiny geometry (the same
12-byte-PSDU, K=8/4096-chunk/1024-window/8-symbol keys as
test_rx_stream) and analyzes every noted program once; each test then
reads the report. The FULL driver — fused link, BER sweep, channel
oracle — rides the tier-2 ``slow`` marker (the CLI path
``python -m ziria_tpu programs`` runs it; its per-program compiles
are real money on a cold cache).

The two cost-pin tests are the ISSUE 9 satellite: the streaming
chunk-scan and stream-decode programs' FLOPs / bytes-accessed pinned
within a generous factor of today's values, so an accidental
recompute (e.g. a dropped ``lax.scan`` carry re-evaluating the chunk)
fails tier-1 loudly instead of halving throughput silently.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ziria_tpu.phy.wifi import rx
from ziria_tpu.utils import programs as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_BYTES = 12                     # the suite's standard on-air PSDU
CHUNK, FRAME_LEN, K, SYM_B = 4096, 1024, 8, 8

# Today's XLA cost-analysis values for the two streaming programs at
# the canonical geometry (jax 0.4.37, CPU backend — the backend the
# tier-1 gate runs on). Bounds are deliberately generous (a jax
# version bump may reshuffle fusion a bit) but tight enough that a
# doubled chunk evaluation (~2x flops AND bytes) fails:
#   lower = pin / 3, upper = pin * 1.8
STREAM_CHUNK_PIN = {"flops": 11732372.0, "bytes_accessed": 3172926.0}
STREAM_DECODE_PIN = {"flops": 30006368.0, "bytes_accessed": 72476368.0}
# the ISSUE 20 fused twin: the same stream-decode program with the
# rate-switched fused front (fused_demap=True) — LLRs produced and
# consumed in VMEM, so bytes_accessed drops to ~0.58x the unfused pin
# (the fori-loop kernel body is also what the analytical model
# bills, one sub-block not MIXED_UNROLL straight-line steps)
STREAM_DECODE_FUSED_PIN = {"flops": 31700852.0,
                           "bytes_accessed": 42078772.0}


def _tier1_driver():
    """The cheap subset of programs.run_driver: per-frame receive,
    batched receive (+CRC), one streaming pass, and one multi-stream
    fleet pass — 13 dispatch-site labels, all at geometries other
    tier-1 suites also compile (the fleet pass rides
    test_rx_multistream's S=4 shape)."""
    from ziria_tpu.backend import framebatch
    from ziria_tpu.phy import link
    from ziria_tpu.phy.wifi import tx

    rng = np.random.default_rng(23)
    rates = [6, 54]
    psdus = [rng.integers(0, 256, N_BYTES).astype(np.uint8)
             for _ in rates]
    cap = np.concatenate(
        [np.zeros((50, 2), np.float32),
         np.asarray(tx.encode_frame(psdus[0], rates[0]))], axis=0)
    rx.receive(cap)
    caps = [np.concatenate(
        [np.zeros((50, 2), np.float32),
         np.asarray(tx.encode_frame(p, m, add_fcs=True))], axis=0)
        for p, m in zip(psdus, rates)]
    framebatch.receive_many(caps, check_fcs=True, batched_acquire=True)
    stream, _ = link.stream_many(
        psdus, rates, snr_db=30.0, cfo=1e-4, delay=60, seed=8,
        add_fcs=True, tail=FRAME_LEN)
    framebatch.receive_stream(stream, chunk_len=CHUNK,
                              frame_len=FRAME_LEN,
                              max_frames_per_chunk=K, check_fcs=True,
                              streaming=True)
    streams, _st = link.stream_many_multi(
        [psdus[:1], psdus[1:], [], psdus[:1]],
        [rates[:1], rates[1:], [], rates[:1]],
        snr_db=np.inf, cfo=1e-4, delay=60, seed=9, add_fcs=True,
        tail=FRAME_LEN)
    framebatch.receive_streams(streams, chunk_len=CHUNK,
                               frame_len=FRAME_LEN,
                               max_frames_per_chunk=K, check_fcs=True,
                               multi=True)


@pytest.fixture(scope="module")
def report():
    return P.collect_programs(driver=_tier1_driver)


# ------------------------------------------------------------- acceptance


def test_lists_at_least_10_programs_with_nonzero_cost(report):
    # the ISSUE 9 acceptance shape: >= 10 compiled programs, every one
    # with nonzero flops AND bytes_accessed from XLA cost analysis
    ok = [r for r in report["programs"] if not r.get("error")]
    assert len(ok) >= 10, [r["label"] for r in report["programs"]]
    for r in ok:
        assert r["flops"] > 0, r
        assert r["bytes_accessed"] > 0, r


def test_memory_analysis_fields_present(report):
    ok = [r for r in report["programs"] if not r.get("error")]
    for r in ok:
        assert r["peak_bytes"] >= r["argument_bytes"] >= 0, r
        assert r["output_bytes"] > 0, r


def test_driver_covers_the_streaming_and_batched_factories(report):
    # factories the tier-1 driver exercises must all map back to a
    # noted program; the full-driver CLI covers the rest (slow test)
    uncovered = set(report["uncovered"])
    for fq in ("ziria_tpu.phy.wifi.rx._jit_stream_chunk",
               "ziria_tpu.phy.wifi.rx._jit_stream_decode",
               "ziria_tpu.phy.wifi.rx._jit_stream_chunk_multi",
               "ziria_tpu.phy.wifi.rx._jit_stream_decode_multi",
               "ziria_tpu.phy.wifi.rx._jit_decode_data_mixed",
               "ziria_tpu.phy.wifi.rx._jit_acquire_many",
               "ziria_tpu.phy.wifi.rx._jit_sync_fn",
               "ziria_tpu.phy.wifi.rx._jit_crc_many",
               "ziria_tpu.phy.wifi.tx._jit_encode_many"):
        assert fq not in uncovered, (fq, sorted(uncovered))
    # the reduced driver legitimately skips only these surfaces
    assert uncovered <= {
        "ziria_tpu.phy.channel._jit_impair_many",
        "ziria_tpu.phy.channel._jit_impair_one",
        "ziria_tpu.phy.link._jit_fused_link",
        "ziria_tpu.phy.link._jit_sweep_ber",
        "ziria_tpu.phy.wifi.tx._jit_encode_batch",
    }, sorted(uncovered)


def test_factory_discovery_is_ast_driven():
    facs = P.discovered_factories()
    names = {f"{f['module']}.{f['name']}" for f in facs}
    # the jit factories of the tree are found by the R1 convention —
    # and table/kernel lru_caches (no jit in the body) are NOT
    assert "ziria_tpu.phy.wifi.rx._jit_stream_chunk" in names
    assert "ziria_tpu.phy.wifi.rx._jit_stream_chunk_multi" in names
    assert "ziria_tpu.phy.wifi.rx._jit_stream_decode_multi" in names
    assert "ziria_tpu.phy.link._jit_fused_link" in names
    assert "ziria_tpu.ops.interleave.interleave_perm" not in names
    assert len(facs) >= 18


# ------------------------------------------------------------- cost pins


def _pin_check(cost, pin):
    for k, v in pin.items():
        assert v / 3 <= cost[k] <= v * 1.8, (
            f"{k}={cost[k]:.4g} outside [{v / 3:.4g}, {v * 1.8:.4g}] "
            f"— the compiled program's work changed materially "
            f"(accidental recompute, dropped fusion, or a real "
            f"optimization: re-pin deliberately)")


def test_stream_chunk_cost_pinned():
    # rx.stream_chunk_graph behind _jit_stream_chunk at the canonical
    # (K=8, 1024-window, 8-symbol) geometry on the 4096-sample chunk
    fn = rx._jit_stream_chunk(K, FRAME_LEN, SYM_B)
    S, i32 = jax.ShapeDtypeStruct, jnp.int32
    cost = P.cost_of(fn, S((CHUNK, 2), jnp.float32), S((), i32),
                     S((), i32), S((), i32))
    _pin_check(cost, STREAM_CHUNK_PIN)


def test_stream_decode_cost_pinned():
    # _jit_stream_decode (row-select + mixed decode + masked CRC) at
    # the same geometry; a dropped carry re-evaluating the decode
    # would ~double both pinned numbers
    need_b = rx.FRAME_DATA_START + 80 * SYM_B
    fn = rx._jit_stream_decode(SYM_B, None, None, 2)
    S, i32 = jax.ShapeDtypeStruct, jnp.int32
    cost = P.cost_of(fn, S((K, need_b, 2), jnp.float32), S((K,), i32),
                     S((K,), i32), S((K,), i32), S((K,), i32))
    _pin_check(cost, STREAM_DECODE_PIN)


def test_stream_decode_fused_cost_pinned_below_unfused():
    # the ISSUE 20 acceptance gate: at the suite-shared geometry the
    # fused stream decode must bill STRICTLY fewer bytes than the
    # unfused program it replaces (the whole point of keeping LLRs in
    # VMEM), and its own cost stays pinned so a wrapper regression
    # (e.g. a bank re-materialized per chunk) fails tier-1 loudly
    need_b = rx.FRAME_DATA_START + 80 * SYM_B
    S, i32 = jax.ShapeDtypeStruct, jnp.int32
    avals = (S((K, need_b, 2), jnp.float32), S((K,), i32),
             S((K,), i32), S((K,), i32), S((K,), i32))
    cost_u = P.cost_of(rx._jit_stream_decode(SYM_B, None, None, 2),
                       *avals)
    cost_f = P.cost_of(
        rx._jit_stream_decode(SYM_B, None, None, 2, False, True),
        *avals)
    _pin_check(cost_f, STREAM_DECODE_FUSED_PIN)
    assert cost_f["bytes_accessed"] < cost_u["bytes_accessed"], (
        cost_f, cost_u)


# ----------------------------------------------------------- observatory


def test_note_site_is_free_when_idle():
    # no active observatory: note_site returns before any aval work,
    # and nothing is recorded anywhere
    obs = P.Observatory()
    P.note_site("nope", None, object())
    assert obs.notes == {}


def test_site_costs_join_on_dispatch_labels(report):
    labels = {r["label"] for r in report["programs"]}
    for lbl in ("rx.stream_chunk", "rx.stream_decode",
                "rx.stream_chunk_multi", "rx.stream_decode_multi",
                "rx.decode_mixed", "rx.crc_many", "rx.acquire_many",
                "tx.encode_many"):
        assert lbl in labels, sorted(labels)


def test_roofline_math_and_peaks_table():
    # 1 GB in 1 ms = 1000 GB/s; v5e peak 819 GB/s
    r = P.roofline(1e-3, bytes_accessed=1e9, flops=2e9,
                   device_kind="TPU v5 lite")
    assert r["achieved_gbps"] == pytest.approx(1000.0)
    assert r["pct_hbm_peak"] == pytest.approx(100 * 1000 / 819.0,
                                              rel=1e-3)
    assert r["achieved_gflops"] == pytest.approx(2000.0)
    assert r["pct_flops_peak"] == pytest.approx(
        100 * 2.0 / 197.0, rel=1e-3)


def test_unknown_device_kind_reports_absolutes_without_pct():
    r = P.roofline(1e-3, bytes_accessed=1e9, flops=1e9,
                   device_kind="TPU v9 hypothetical")
    assert "achieved_gbps" in r and "achieved_gflops" in r
    assert "pct_hbm_peak" not in r and "pct_flops_peak" not in r
    assert P.peaks_for("cpu") is None
    assert P.peaks_for(None) is None
    assert P.peaks_for("v5e") == {"hbm_gbps": 819.0,
                                  "peak_tflops": 197.0}


def test_hlo_dump_writes_program_text(tmp_path):
    obs = P.Observatory()
    f = jax.jit(lambda x: (x * 2.0).sum())
    with P.observing(obs):
        x = jnp.ones((16,), jnp.float32)
        P.note_site("toy.sum", f, x)
        f(x)
    recs = obs.analyze(hlo_dump=str(tmp_path))
    assert len(recs) == 1 and recs[0]["label"] == "toy.sum"
    assert os.path.exists(recs[0]["hlo_path"])
    text = open(recs[0]["hlo_path"]).read()
    assert "HloModule" in text or "module" in text


def test_observatory_dedupes_geometry_and_counts_calls():
    obs = P.Observatory()
    f = jax.jit(lambda x: x + 1)
    with P.observing(obs):
        for _ in range(3):
            P.note_site("toy.add", f, jnp.ones((4,), jnp.float32))
        P.note_site("toy.add", f, jnp.ones((8,), jnp.float32))
    assert len(obs.notes) == 2
    counts = sorted(n.calls for n in obs.notes.values())
    assert counts == [1, 3]


def test_bench_roofline_prefers_cost_and_keeps_hand_crosscheck():
    # bench.py's _roofline: with an XLA cost dict the achieved numbers
    # come from the compiled graph and the hand formula stays as the
    # cross-check column; without one the source says hand_estimate
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    cost = {"flops": 2e12, "bytes_accessed": 819e9 / 2}
    r = bench._roofline(128, 4720, 54, 8000, 1.0,
                        device_kind="TPU v5 lite", cost=cost)
    assert r["source"] == "xla_cost_analysis"
    assert r["pct_hbm_peak"] == pytest.approx(50.0, rel=1e-3)
    assert "hand_gbps" in r and "hand_tflops" in r
    r2 = bench._roofline(128, 4720, 54, 8000, 1.0)
    assert r2["source"] == "hand_estimate"
    assert "pct_hbm_peak" not in r2       # no device kind -> no pct


# ------------------------------------------------------------ full driver


@pytest.mark.slow
def test_full_driver_covers_every_factory():
    rep = P.collect_programs()
    assert rep["uncovered"] == [], rep["uncovered"]
    assert rep["factories_covered"] == rep["factories_discovered"]
    assert rep["programs_analyzed"] >= 10


@pytest.mark.slow
def test_cli_programs_json_subprocess():
    # the acceptance surface end to end: `python -m ziria_tpu programs
    # --json` on a box whose default backend may even be a hung TPU
    # probe — the subcommand pins CPU itself
    out = subprocess.run(
        [sys.executable, "-m", "ziria_tpu", "programs", "--json"],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    assert out.returncode == 0, out.stderr[-800:]
    j = json.loads(out.stdout.strip().splitlines()[-1])
    assert j["platform"] == "cpu"
    ok = [r for r in j["programs"] if not r.get("error")
          and r.get("flops") and r.get("bytes_accessed")]
    assert len(ok) >= 10
