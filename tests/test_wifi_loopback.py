"""Full PHY loopback: TX -> channel impairments -> RX (config #5's
single-frame form). The reference's equivalent is the golden
TX-to-RX file tests (SURVEY.md §4); here the channel is synthetic and
the assertion is exact PSDU recovery + FCS."""

import jax
import numpy as np
import pytest

from ziria_tpu.ops import cplx
from ziria_tpu.phy import channel
from ziria_tpu.phy.wifi import rx, tx
from ziria_tpu.phy.wifi.params import RATES, n_symbols
from ziria_tpu.utils.bits import bytes_to_bits
from ziria_tpu.utils.diff import assert_stream_eq

RNG = np.random.default_rng(3)
KEY = jax.random.PRNGKey(0)


def make_frame(rate, n_bytes=60, add_fcs=True):
    psdu = RNG.integers(0, 256, n_bytes).astype(np.uint8)
    wave = tx.encode_frame(psdu, rate, add_fcs=add_fcs)
    bits = np.asarray(bytes_to_bits(psdu))
    return psdu, bits, wave


@pytest.mark.parametrize("rate", [6, 9, 12, 18, 24, 36, 48, 54])
def test_loopback_clean_aligned(rate):
    """Aligned, no channel: decode_signal + static data decode."""
    psdu, bits, wave = make_frame(rate, n_bytes=53)
    frame = np.asarray(wave)
    rate_bits, length, parity_ok = rx.decode_signal(frame)
    assert bool(np.asarray(parity_ok))
    assert int(np.asarray(length)) == 53 + 4  # FCS appended
    n_sym = n_symbols(53 + 4, RATES[rate])
    got, _ = rx.decode_data_static(frame, RATES[rate], n_sym, 8 * (53 + 4))
    got = np.asarray(got)
    # the PSDU region starts with the original payload bits (FCS after)
    assert_stream_eq(got[: 8 * 53], bits, name=f"loopback@{rate}")


@pytest.mark.parametrize("rate", [6, 24, 54])
def test_receive_full_chain_with_impairments(rate):
    """Detection + timing + CFO + phase + noise + delay: the whole
    receiver driver."""
    psdu, bits, wave = make_frame(rate, n_bytes=40)
    k1, k2, k3 = jax.random.split(KEY, 3)
    x = channel.delay(k1, wave, n_before=333, n_after=200)
    x = channel.apply_cfo(x, 2e-4)          # ~6 kHz-ish at 20 MS/s
    x = channel.apply_phase(x, 0.7)
    x = channel.awgn(k2, x, snr_db=25.0)
    res = rx.receive(np.asarray(x), check_fcs=True)
    assert res.ok
    assert res.rate_mbps == rate
    assert res.length_bytes == 44          # 40 + FCS
    assert res.crc_ok
    assert_stream_eq(res.psdu_bits[: 8 * 40], bits, name=f"rx@{rate}")


def test_receive_rejects_noise_only():
    k = jax.random.PRNGKey(9)
    noise = jax.random.normal(k, (4096, 2)) * 0.1
    res = rx.receive(np.asarray(noise))
    assert not res.ok


def test_receive_multipath():
    psdu, bits, wave = make_frame(24, n_bytes=30)
    taps = np.zeros((8, 2), np.float32)
    taps[0] = [1.0, 0.0]
    taps[3] = [0.15, -0.1]
    taps[7] = [0.05, 0.05]
    k1, k2 = jax.random.split(KEY)
    x = channel.delay(k1, channel.multipath(wave, taps), n_before=100,
                      n_after=100)
    x = channel.awgn(k2, x, snr_db=28.0)
    res = rx.receive(np.asarray(x), check_fcs=True)
    assert res.ok and res.crc_ok
    assert_stream_eq(res.psdu_bits[: 8 * 30], bits, name="rx@multipath")


def test_corrupted_frame_fails_crc():
    psdu, bits, wave = make_frame(12, n_bytes=20)
    x = np.asarray(channel.delay(KEY, wave, n_before=50, n_after=50)).copy()
    # erase three whole DATA symbols — beyond what the code can correct
    x[50 + 400: 50 + 640] = 0.0
    res = rx.receive(x, check_fcs=True)
    # frame is found and parsed, but the FCS must catch the corruption
    if res.ok:
        assert res.crc_ok is False


def test_truncated_capture_with_padding_not_false_success():
    """A capture cut mid-frame must not decode bucket padding as DATA."""
    psdu, bits, wave = make_frame(6, n_bytes=200)   # long frame
    x = np.asarray(channel.delay(KEY, wave, n_before=1000, n_after=0))
    cut = x[: 1000 + 1500]                          # mid-DATA truncation
    res = rx.receive(cut)
    assert not res.ok


def test_receive_bucketed_jit_cache():
    """Streaming-grade dispatch (VERDICT r1 weak #3): 20 frames of
    distinct PSDU lengths must decode exactly while the data-decode jit
    cache stays within the power-of-two bucket bound, not one entry per
    length."""
    rx._jit_decode_data_bucketed.cache_clear()
    lengths = list(range(21, 401, 20))          # 20 distinct lengths
    for i, n in enumerate(lengths):
        psdu = RNG.integers(0, 256, n).astype(np.uint8)
        wave = tx.encode_frame(psdu, 24, add_fcs=True)
        k = jax.random.PRNGKey(100 + i)
        x = channel.delay(k, wave, n_before=150, n_after=90)
        res = rx.receive(np.asarray(x), check_fcs=True)
        assert res.ok and res.rate_mbps == 24, f"len {n}: {res}"
        assert res.length_bytes == n + 4
        assert res.crc_ok
        assert_stream_eq(res.psdu_bits[: 8 * n],
                         np.asarray(bytes_to_bits(psdu)),
                         name=f"bucketed@{n}")
    buckets = {rx._sym_bucket(n_symbols(n + 4, RATES[24]))
               for n in lengths}
    info = rx._jit_decode_data_bucketed.cache_info()
    assert info.currsize == len(buckets) <= 5, \
        f"cache {info.currsize} entries for {len(buckets)} buckets"


def test_bucketed_equals_static_decode():
    """The bucketed (padded + masked) decode must equal the exact-shape
    static decode bit for bit, including at a non-power-of-two symbol
    count."""
    for rate, n_bytes in ((6, 37), (24, 53), (54, 200)):
        psdu, bits, wave = make_frame(rate, n_bytes=n_bytes)
        frame = np.asarray(wave)
        rp = RATES[rate]
        n_sym = n_symbols(n_bytes + 4, rp)
        want, _ = rx.decode_data_static(frame, rp, n_sym,
                                        8 * (n_bytes + 4))
        n_sym_b = rx._sym_bucket(n_sym)
        pad = np.zeros((rx.FRAME_DATA_START + 80 * n_sym_b, 2),
                       np.float32)
        pad[: frame.shape[0]] = frame[: pad.shape[0]]
        clear = rx.decode_data_bucketed(
            jax.numpy.asarray(pad), rp, n_sym_b,
            jax.numpy.int32(n_sym * rp.n_dbps))
        got = np.asarray(clear)[16: 16 + 8 * (n_bytes + 4)]
        assert_stream_eq(got, np.asarray(want),
                         name=f"bucketed-vs-static@{rate}")


def test_receive_windowed_viterbi_matches_exact():
    """receive(viterbi_window=...) — the sliding-window parallel
    Viterbi serving the single-frame driver — returns the identical
    PSDU (and FCS verdict) as the exact decode on an impaired capture
    long enough to actually window (>= 2 windows of 512)."""
    psdu, bits, wave = make_frame(54, n_bytes=200)
    k1, k2, _ = jax.random.split(KEY, 3)
    x = channel.delay(k1, wave, n_before=120, n_after=80)
    x = channel.apply_cfo(x, 1e-4)
    x = np.asarray(channel.awgn(k2, x, snr_db=26.0))
    exact = rx.receive(x, check_fcs=True)
    win = rx.receive(x, check_fcs=True, viterbi_window=512)
    assert exact.ok and win.ok
    assert win.rate_mbps == exact.rate_mbps
    assert win.length_bytes == exact.length_bytes
    assert bool(win.crc_ok) and bool(exact.crc_ok)
    np.testing.assert_array_equal(win.psdu_bits, exact.psdu_bits)
    assert_stream_eq(win.psdu_bits[: 8 * 200], bits, name="rx-windowed")
