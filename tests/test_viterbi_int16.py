"""int16 saturating-metric Viterbi (docs/quantized_viterbi.md).

The quantized kernel's contract has three layers, each pinned here:

1. the int16 Pallas ACS kernel decodes bit-exactly what the f32
   ``lax.scan`` oracle decodes on the SAME quantized inputs (integer
   branch metrics are exact in both arithmetics; the per-block renorm
   + saturation only ever clips floored states) — across batch sizes
   and frame lengths including the bench shape;
2. the int16 scan oracle (``viterbi_decode_int16``) agrees with both,
   so the quantized semantics have a readable reference;
3. on RAW noisy inputs (where quantization rounding may legitimately
   flip a decision) the end-to-end int16 decode stays within the same
   bounded-BER envelope as the windowed decode's guard
   (tests/test_windowed_ber_guard.py).
"""

import importlib.util
import os

import jax
import numpy as np
import pytest

from ziria_tpu.ops import viterbi, viterbi_pallas

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "windowed_ber", os.path.join(_REPO, "tools", "windowed_ber.py"))
_wb = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_wb)
_frames = _wb.make_coded_frames     # ONE signal recipe with the study

BENCH_T = 8208      # 1000-byte 54 Mbps DATA trellis (bench shape)


def _oracle_f32(qllrs):
    """The f32 lax.scan decode of already-quantized integer inputs —
    the oracle the acceptance contract names. Integer-valued branch
    metrics are exact in f32 (|path metric| < 2^24 for any T here),
    so this is the unquantized-arithmetic ground truth."""
    return np.asarray(jax.vmap(viterbi.viterbi_decode)(
        np.asarray(qllrs, np.float32)))


@pytest.mark.parametrize("B", [8, 128])
@pytest.mark.parametrize("T", [256, 1000])
def test_i16_kernel_bit_exact_vs_f32_scan_oracle(B, T):
    rng = np.random.default_rng(B * 10000 + T)
    _msgs, llrs = _frames(rng, B, T, amp=1.2)
    q, _scale = viterbi.quantize_llrs(llrs)
    want = _oracle_f32(q)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int16"))
    np.testing.assert_array_equal(got, want)
    # the int16 scan oracle sits between the two: same bits again
    scan_i16 = np.asarray(jax.vmap(viterbi.viterbi_decode_int16)(q))
    np.testing.assert_array_equal(scan_i16, want)


@pytest.mark.slow
def test_i16_kernel_bit_exact_bench_shape():
    # tier-2: ~30s of interpret-mode Pallas at the full 8208-step
    # trellis — the {256, 1000} matrix above covers the kernel logic
    # in tier-1; this pins the headline geometry for chip windows
    # the headline geometry: 128 lanes x the 8208-step DATA trellis.
    # The interpret-mode kernel walks one 128-lane tile either way, so
    # B=8 (padded to the tile) and B=128 both ride this check: decode
    # B=128, then re-decode the first 8 lanes as their own batch
    # (per-frame quantization scales make the two decodes of a lane
    # identical by construction — this pins it).
    rng = np.random.default_rng(2026)
    _msgs, llrs = _frames(rng, 128, BENCH_T, amp=1.2)
    q, _scale = viterbi.quantize_llrs(llrs)
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int16"))
    np.testing.assert_array_equal(got, _oracle_f32(q))

    sub = llrs[:8]
    q8, _ = viterbi.quantize_llrs(sub)
    got8 = np.asarray(viterbi_pallas.viterbi_decode_batch(
        sub, metric_dtype="int16"))
    np.testing.assert_array_equal(got8, _oracle_f32(q8))


def _scan_i16(x):
    """The quantized decode's scan engine (quantize + int16 oracle) —
    the same semantics the Pallas kernel computes (pinned by the
    kernel-parity tests above), without interpret-mode kernel cost."""
    q, _ = viterbi.quantize_llrs(x)
    return np.asarray(jax.vmap(viterbi.viterbi_decode_int16)(q))


def test_i16_on_raw_inputs_bounded_ber():
    # raw noisy floats: rounding at the quantization boundary may
    # legitimately move individual decisions, but the error RATE must
    # stay inside the windowed guard's envelope (same form/margins as
    # tests/test_windowed_ber_guard.py) both at the operating point
    # and below the waterfall
    for seed, amp in ((3, 1.2), (7, 0.9)):
        rng = np.random.default_rng(seed)
        msgs, llrs = _frames(rng, 4, 2048, amp=amp)
        f32 = np.asarray(jax.vmap(viterbi.viterbi_decode)(llrs))
        i16 = _scan_i16(llrs)
        ber_f = (f32 != msgs).mean()
        ber_q = (i16 != msgs).mean()
        assert abs(ber_q - ber_f) < 0.02 * max(ber_f, 1e-9) + 2e-3, \
            (amp, ber_f, ber_q)


def _scan_i16_raw(q):
    """int16-input scan engine: decode pre-quantized integers as-is
    (what the production batch decode does with int16 input)."""
    return np.asarray(jax.vmap(viterbi.viterbi_decode_int16)(
        np.asarray(q, np.int32)))


def test_windowed_i16_matches_full_i16_at_operating_point():
    # the two knobs compose. The windowed decode quantizes PER FRAME
    # **before** cutting windows, so every window slices the exact
    # integers the full-frame decode consumes, and at the operating
    # amplitude the windowed int16 decode reproduces the full int16
    # decode bit-for-bit (the same survivor-merge argument as the f32
    # windowed guard). Against the f32 decode only the BER envelope is
    # promised — quantization rounding may legitimately move near-tie
    # decisions. (scan engines via _decode injection, the windowed-
    # guard idiom — the windowing math is what's under test, not the
    # kernel; metric_dtype="int16" makes the windowed path hand the
    # injected engine int16 windows)
    rng = np.random.default_rng(5)
    msgs, llrs = _frames(rng, 4, 2048, amp=1.2)
    full = _scan_i16(llrs)
    win = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=512, metric_dtype="int16", _decode=_scan_i16_raw))
    np.testing.assert_array_equal(win, full)
    assert (full != msgs).mean() < 0.05     # an OPERATING decoder
    f32 = np.asarray(viterbi_pallas.viterbi_decode_batch_windowed(
        llrs, window=512,
        _decode=lambda x: jax.vmap(viterbi.viterbi_decode)(x)))
    assert abs((win != msgs).mean() - (f32 != msgs).mean()) \
        < 0.02 * max((f32 != msgs).mean(), 1e-9) + 2e-3


def test_quantize_llrs_contract():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 64, 2)).astype(np.float32) * 7.5
    q, scale = viterbi.quantize_llrs(x)
    q, scale = np.asarray(q), np.asarray(scale)
    assert q.dtype == np.int16
    assert scale.shape == (4, 1, 1)     # one scale PER FRAME
    # every lane's own peak maps to QMAX — no lane's quantization
    # depends on its batch-mates (the receive_many == receive
    # bit-identity hinges on this)
    np.testing.assert_array_equal(
        np.abs(q).max(axis=(1, 2)), [viterbi.QUANT_MAX] * 4)
    np.testing.assert_array_equal(
        q, np.clip(np.round(x * scale),
                   -viterbi.QUANT_MAX, viterbi.QUANT_MAX))
    # a single frame quantizes identically to its batched self
    q0, s0 = viterbi.quantize_llrs(x[0])
    assert np.asarray(s0).shape == ()
    np.testing.assert_array_equal(np.asarray(q0), q[0])


def test_saturation_touches_only_floored_states():
    # adversarial drive: noise-free max-amplitude inputs at exactly
    # +-QUANT_MAX (quantization scale = 1, rounding = identity) open
    # the widest possible metric spread — losing states fall 2*127 per
    # step until they pin at the int16 rail — while the surviving path
    # (max renormed to 0 each block) must be untouched: decode still
    # matches the f32 oracle on the same quantized inputs. T matches
    # the [1000-8] parity case so the kernel compile is reused.
    rng = np.random.default_rng(9)
    msgs, llrs = _frames(rng, 8, 1000, amp=1.0)
    llrs = np.sign(llrs - np.float32(1e-7)) * viterbi.QUANT_MAX
    q, _ = viterbi.quantize_llrs(llrs)
    np.testing.assert_array_equal(np.asarray(q), llrs)  # scale == 1
    got = np.asarray(viterbi_pallas.viterbi_decode_batch(
        llrs, metric_dtype="int16"))
    np.testing.assert_array_equal(got, _oracle_f32(q))


def test_metric_dtype_validation():
    x = np.zeros((2, 64, 2), np.float32)
    # int8 became a LEGAL mode in ISSUE 6 (tests/test_viterbi_radix4);
    # the rejection contract moves to genuinely-unknown dtypes
    with pytest.raises(ValueError, match="metric_dtype"):
        viterbi.viterbi_decode(x[0], metric_dtype="int4")
    with pytest.raises(ValueError, match="metric_dtype"):
        viterbi_pallas.viterbi_decode_batch(x, metric_dtype="bfloat16")
    # None and the explicit default are the same legal surface
    a = np.asarray(viterbi.viterbi_decode(x[0], n_bits=8))
    b = np.asarray(viterbi.viterbi_decode(x[0], n_bits=8,
                                          metric_dtype="float32"))
    np.testing.assert_array_equal(a, b)


def test_cli_choices_mirror_metric_dtypes():
    # runtime/cli.py hardcodes the --viterbi-metric choices so --help
    # stays import-light; this pins them to the ops-layer registry
    from ziria_tpu.runtime.cli import build_parser
    for a in build_parser()._actions:
        if a.dest == "viterbi_metric":
            assert tuple(a.choices) == viterbi.METRIC_DTYPES
            return
    raise AssertionError("--viterbi-metric flag missing")


def test_env_mode_reaches_staged_viterbi_soft(monkeypatch):
    # ZIRIA_VITERBI_METRIC routes every STAGED viterbi_soft through
    # the quantized decode, and the mode is part of the backend's
    # compile cache key — flipping the env after tracing must RE-trace
    # (ADVICE r5 #1), observable here through viterbi_mode()
    import jax.numpy as jnp

    from ziria_tpu.frontend import externals

    monkeypatch.delenv("ZIRIA_VITERBI_WINDOW", raising=False)
    monkeypatch.delenv("ZIRIA_VITERBI_METRIC", raising=False)
    monkeypatch.delenv("ZIRIA_VITERBI_RADIX", raising=False)
    assert externals.viterbi_mode() == (0, "float32", 2)
    monkeypatch.setenv("ZIRIA_VITERBI_METRIC", "int16")
    monkeypatch.setenv("ZIRIA_VITERBI_WINDOW", "512")
    assert externals.viterbi_mode() == (512, "int16", 2)
    # int8 became a legal metric in ISSUE 6; the reject contract moves
    # to genuinely-unknown dtypes
    monkeypatch.setenv("ZIRIA_VITERBI_METRIC", "int4")
    with pytest.raises(ValueError, match="ZIRIA_VITERBI_METRIC"):
        externals.viterbi_mode()
    monkeypatch.setenv("ZIRIA_VITERBI_METRIC", "int16")

    # staged decode agrees with the f32 staged decode at operating SNR
    vs = externals.EXTERNALS["viterbi_soft"]
    rng = np.random.default_rng(4)
    n = 600
    msgs, frames = _frames(rng, 1, n, amp=3.0)
    llrs = frames[0].reshape(-1)
    got = np.asarray(jax.jit(
        lambda x: vs(x, n, n))(jnp.asarray(llrs)))
    np.testing.assert_array_equal(got[:n], msgs[0])
