"""Driver contract of bench.py: ONE parseable JSON line on stdout with
the keys the round harness records (metric/value/unit/vs_baseline),
whatever the backend's state. Runs the real parent with --no-tpu (the
numpy baseline path + last_good promotion logic) in a subprocess, like
the driver does."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_no_tpu_emits_driver_contract(tmp_path):
    # BENCH_TRAJECTORY redirect: the run must append its perf-ledger
    # records (ISSUE 9 acceptance: a fresh run appends), but a TEST
    # run must never dirty the committed BENCH_TRAJECTORY.jsonl
    traj = tmp_path / "traj.jsonl"
    env = dict(os.environ, BENCH_TRAJECTORY=str(traj))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--no-tpu"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-500:]
    recs = [json.loads(ln) for ln in
            traj.read_text().strip().splitlines()]
    stages = {r["stage"] for r in recs}
    assert "numpy_baseline" in stages and "result" in stages
    for r in recs:
        for key in ("run_id", "unix", "stage", "metric", "value",
                    "platform", "partial", "direction"):
            assert key in r, (key, r)
    lines = [ln for ln in out.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, f"expected ONE JSON line, got {len(lines)}"
    j = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in j, f"missing driver key {key}"
    assert j["metric"] == "80211a_rx_samples_per_sec_per_chip"
    assert j["value"] > 0 and j["vs_baseline"] > 0
    # the pinned denominator is committed; every published multiple
    # divides by it. The contract is "a pinned denominator is used",
    # not a specific value — compare against BASELINE.json so a
    # legitimate re-pin (bench.py --pin-baseline) does not break the
    # suite (ADVICE r5 #5)
    with open(os.path.join(REPO, "BASELINE.json")) as f:
        pinned = json.load(f)["pinned_baseline"]["sps"]
    assert j.get("pinned_baseline_sps") == pinned
    # whatever value is published, it is either a real capture
    # (platform stamped) or the clearly-labelled baseline fallback
    assert j.get("platform") or j.get("tpu", "").startswith("unavail")
