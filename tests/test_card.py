"""Cardinality (SDF-rate) analysis tests."""

from ziria_tpu import take, takes, emit1, emits, ret, seq, let, zmap, repeat, pipe
from ziria_tpu.core import ir
from ziria_tpu.core.card import CCard, TCard, DYN, cardinality, steady_state


def test_basic_computers():
    assert cardinality(take) == CCard(1, 0)
    assert cardinality(takes(5)) == CCard(5, 0)
    assert cardinality(emit1(1)) == CCard(0, 1)
    assert cardinality(emits([1, 2], 2)) == CCard(0, 2)
    assert cardinality(ret(0)) == CCard(0, 0)


def test_bind_sums():
    c = let("x", takes(3), emits(lambda env: env["x"], 3))
    assert cardinality(c) == CCard(3, 3)


def test_repeat_gives_rate():
    c = repeat(let("x", take, emit1(lambda env: env["x"])))
    assert cardinality(c) == TCard(1, 1)


def test_map_rate():
    assert cardinality(zmap(lambda x: x, 4, 2)) == TCard(4, 2)


def test_pipe_steady_state_rates():
    # 1->3 then 2->1 : lcm(3,2)=6 -> up fires 2x, down 3x : rate 2 -> 3
    c = pipe(zmap(lambda x: x, 1, 3), zmap(lambda x: x, 2, 1))
    assert cardinality(c) == TCard(2, 3)


def test_while_dynamic():
    c = ir.While(lambda env: True, emit1(1))
    assert cardinality(c) == DYN


def test_for_static():
    c = ir.For("i", 4, let("x", take, emit1(lambda env: env["x"])))
    assert cardinality(c) == CCard(4, 4)


def test_steady_state_plan():
    stages = [zmap(lambda x: x, 1, 3), zmap(lambda x: x, 2, 1),
              zmap(lambda x: x, 3, 3)]
    ss = steady_state(stages)
    # stage0 o=3, stage1 i=2 -> lcm 6: reps (2,3); stage1 out 3*1=3, stage2
    # i=3 -> reps (2,3,1); consumes 2, emits 3
    assert ss.reps == (2, 3, 1)
    assert ss.take == 2
    assert ss.emit == 3


def test_steady_state_none_for_dynamic():
    stages = [zmap(lambda x: x), ir.While(lambda env: True, emit1(1))]
    assert steady_state(stages) is None


def test_steady_state_none_for_interior_zero_rates():
    sink = repeat(let("x", take, ret(0)))       # TCard(1, 0)
    source = repeat(emit1(1))                   # TCard(0, 1)
    f = zmap(lambda x: x)
    assert steady_state([f, sink, f]) is None   # sink mid-chain
    assert steady_state([f, source]) is None    # source downstream
    # sink in last position and source in first position ARE plannable
    assert steady_state([f, sink]) is not None
    assert steady_state([source, f]) is not None


def test_steady_state_empty():
    assert steady_state([]) is None
