"""Regenerate the checked-in golden files for examples/*.zir.

The reference ships per-block tests as (program, .infile,
.outfile.ground) triples compared by BlinkDiff (SURVEY.md §4). This
script writes the same artifacts under examples/golden/: deterministic
inputs, and ground-truth outputs produced by the **interpreter oracle**
(never the jit backend — the golden test's whole point is that the
compiled path must match the oracle; INTERP_CASES below are the
documented exception, replayed on the interpreter because their
unrolled jit graphs take minutes of XLA compile on CPU).

    python examples/make_golden.py          # writes examples/golden/

Run the goldens with ``pytest tests/test_golden.py``.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

HERE = os.path.dirname(os.path.abspath(__file__))
GOLD = os.path.join(HERE, "golden")

# (example, input type, input builder, dbg|bin)
def _bits(n, seed):
    return np.random.default_rng(seed).integers(0, 2, n).astype(np.uint8)


def _iq(n, seed):
    return np.random.default_rng(seed).integers(
        -600, 600, (n, 2)).astype(np.int16)


def _llrs(n, seed):
    return (4.0 * np.random.default_rng(seed).standard_normal(n)) \
        .astype(np.float32)


CASES = [
    ("scrambler", "bit", lambda: _bits(512, 100), "dbg"),
    ("fir", "int32",
     lambda: (2000 * np.sin(np.arange(256) / 7)).astype(np.int32), "dbg"),
    ("fft64", "complex16", lambda: _iq(256, 101), "dbg"),
    ("interleaver", "bit", lambda: _bits(480, 102), "dbg"),
    ("wifi_tx_bpsk", "bit", lambda: _bits(384, 103), "bin"),
    ("lut_map", "int8",
     lambda: np.arange(-128, 128, dtype=np.int8), "dbg"),
    ("qam16", "bit", lambda: _bits(64 * 4, 104), "dbg"),
    # RX-side per-block corpus (VERDICT r1 #7): demap at all four
    # constellations, soft deinterleave, depuncture, pilot tracking —
    # the reference's densest golden-test area (SURVEY.md §2.3)
    ("demap_bpsk", "complex16", lambda: _iq(256, 105), "dbg"),
    ("demap_qpsk", "complex16", lambda: _iq(256, 106), "dbg"),
    ("demap_qam16", "complex16", lambda: _iq(256, 107), "dbg"),
    ("demap_qam64", "complex16", lambda: _iq(256, 108), "bin"),
    ("deinterleave_bpsk", "bit", lambda: _bits(480, 109), "dbg"),
    ("deinterleave_qam16", "float32", lambda: _llrs(192 * 4, 110), "dbg"),
    ("depuncture_23", "float32", lambda: _llrs(192, 111), "dbg"),
    ("depuncture_34", "float32", lambda: _llrs(192, 112), "bin"),
    ("pilot_track", "complex16", lambda: _iq(52 * 6, 113), "dbg"),
    # RX front-end DC removal (reference receiver's first block)
    ("dc_remove", "complex16", lambda: _iq_dc(512, 120), "dbg"),
    # stdlib (v_* / crc32) examples — VERDICT r1 #8
    ("crc_frame", "bit", lambda: _bits(512, 114), "bin"),
    ("correlator", "complex16", lambda: _iq(320, 115), "dbg"),
    # int16 fixed-point complex16 policy (VERDICT r1 #6): exact
    # integer outputs for scrambler -> encoder -> modulator
    ("tx_qpsk_fxp", "bit", lambda: _bits(384, 116), "bin"),
    # all-integer FM discriminator: CORDIC atan2 ext over a
    # frequency-modulated integer tone (non-WiFi corpus member)
    ("fm_demod", "complex16", lambda: _fm_input(512, 125), "dbg"),
    # the COMPLETE 6 Mbps transmitter as a program of the framework:
    # preamble + SIGNAL + DATA symbols (VERDICT r1 #2's TX-side dual)
    ("wifi_tx_full", "bit", lambda: _bits(800, 117), "bin"),
    # inferred AutoLUT (lutinfer): arr[8] bit and int8 funs with no
    # declared domains; replayed with --autolut (AUTOLUT_CASES)
    ("pack_bits", "bit", lambda: _bits(8 * 96, 118), "dbg"),
    # the FLAGSHIP as a checked-in golden: an impaired 24 Mbps capture
    # through the in-language receiver; replayed on the hybrid backend
    # (HYBRID_CASES) — detection, CFO, SIGNAL parse, rate dispatch and
    # decode all pinned by one file pair
    ("wifi_rx", "complex16", lambda: _rx_capture(24, 60, 119), "bin"),
    # the FIXED-POINT in-language receiver (--fxp-complex16): same
    # capture recipe at 36 Mbps; integer detect/CFO/equalize/demap
    # pinned by the pair, replayed hybrid
    ("wifi_rx_fxp", "complex16", lambda: _rx_capture(36, 70, 123),
     "bin"),
    # the multi-rate in-language TRANSMITTER: one 36 Mbps frame,
    # in-band [rate, len, bits...] header (INTERP_CASES — runtime-
    # parameterized whole-frame program)
    ("wifi_tx_rates", "int32", lambda: _tx_rates_input(36, 54, 121),
     "bin"),
    # in-language LOOPBACK: MAC frames -> fcs_add >>> tx_frame >>> rx
    # across two rates in one stream; output must equal the payload
    # bits exactly (FCS generated TX-side, validated+stripped RX-side)
    ("wifi_loopback", "int32", lambda: _loopback_input(122), "bin"),
    # the ALL-INTEGER loopback (--fxp-complex16): fcs_add >>>
    # tx_frame_fxp >>> rx_fxp, zero floating point in the sample
    # domain on either side
    ("wifi_loopback_fxp", "int32", lambda: _loopback_input(124),
     "bin"),
]


def _loopback_input(seed):
    import numpy as np
    rng = np.random.default_rng(seed)
    stream = []
    for rate, n_bytes in ((6, 20), (24, 30)):
        bits = rng.integers(0, 2, 8 * n_bytes).astype(np.int32)
        stream += [rate, n_bytes] + bits.tolist()
    return np.asarray(stream, np.int32)


def _tx_rates_input(mbps, n_bytes, seed):
    import numpy as np

    from ziria_tpu.utils.bits import bytes_to_bits
    rng = np.random.default_rng(seed)
    psdu = rng.integers(0, 256, n_bytes).astype(np.uint8)
    bits = np.asarray(bytes_to_bits(psdu)).astype(np.int32)
    return np.concatenate([[mbps, n_bytes], bits]).astype(np.int32)


def _iq_dc(n, seed):
    # complex16 samples riding a strong DC offset for dc_remove.zir
    import numpy as np
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 120, (n, 2)) + np.array([310.0, -170.0])
    return np.clip(np.round(x), -32768, 32767).astype(np.int16)


def _fm_input(n, seed):
    # FM-modulated integer tone: phase increments swing +-0.3 rad
    import numpy as np
    rng = np.random.default_rng(seed)
    freq = 0.3 * np.sin(2 * np.pi * np.arange(n) / 100.0) \
        + 0.05 * rng.standard_normal(n)
    ph = np.cumsum(freq)
    x = np.round(1500 * np.exp(1j * ph))
    return np.stack([x.real, x.imag], -1).astype(np.int16)


def _rx_capture(mbps, n_bytes, seed):
    # main() pins the CPU platform before any case builder runs
    from ziria_tpu.phy.channel import impaired_capture

    _psdu, xi = impaired_capture(mbps, n_bytes, seed, floor=0.02,
                                 add_fcs=True)
    return xi

# cases compiled under the fixed-point complex16 policy
# (--fxp-complex16 on replay)
FXP_CASES = {"tx_qpsk_fxp", "wifi_rx_fxp", "wifi_loopback_fxp",
             "fm_demod"}

# cases replayed on the interpreter backend (whole-frame programs whose
# fully-unrolled jit graphs take minutes of XLA compile on CPU)
INTERP_CASES = {"wifi_tx_full", "wifi_tx_rates", "wifi_loopback",
                "wifi_loopback_fxp"}

# cases replayed with --autolut: the inferred-LUT rewrite must leave
# the golden output untouched (flag invariance)
AUTOLUT_CASES = {"pack_bits", "lut_map"}

# cases replayed on the hybrid backend (dynamic control; heavy
# do-blocks jit) — ground truth still comes from the interpreter
HYBRID_CASES = {"wifi_rx", "wifi_rx_fxp"}


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ziria_tpu.frontend import compile_file
    from ziria_tpu.interp.interp import run
    from ziria_tpu.runtime.buffers import StreamSpec, write_stream

    os.makedirs(GOLD, exist_ok=True)
    only = set(sys.argv[1:])          # regenerate a subset by name
    unknown = only - {name for name, *_ in CASES}
    if unknown:
        raise SystemExit(f"unknown case name(s): {sorted(unknown)}; "
                         f"known: {sorted(n for n, *_ in CASES)}")
    for name, in_ty, make, mode in CASES:
        if only and name not in only:
            continue
        src = os.path.join(HERE, f"{name}.zir")
        prog = compile_file(src, fxp_complex16=name in FXP_CASES)
        xs = make()
        res = run(prog.comp, list(xs))
        ys = res.out_array()
        write_stream(StreamSpec(ty=in_ty, path=os.path.join(
            GOLD, f"{name}.infile"), mode=mode), xs)
        write_stream(StreamSpec(ty=prog.out_ty or in_ty, path=os.path.join(
            GOLD, f"{name}.outfile.ground"), mode=mode), ys)
        print(f"{name}: {xs.shape[0]} in -> {ys.shape[0]} out "
              f"({mode}, {in_ty} -> {prog.out_ty})")


if __name__ == "__main__":
    main()
